"""Dashboard head: HTTP API over cluster state + Prometheus metrics.

Reference analogue: `dashboard/head.py:81` (aiohttp app with per-subsystem
modules) + `dashboard/state_aggregator.py`.  Re-designed small: one
threaded HTTP server reading the GCS tables directly over the existing
framed-socket client — no agent processes, no driver attach — so it can
run next to the GCS on the head node or anywhere that can reach it.

Endpoints:
  GET /                      tiny HTML overview
  GET /api/nodes             GCS node table
  GET /api/actors            GCS actor table
  GET /api/jobs              job-submission records (GCS KV)
  GET /api/cluster_resources {total, available} aggregated over alive nodes
  GET /api/load              autoscaler load metrics (demand + idle)
  GET /api/placement_groups  cluster PG table
  GET /api/tasks             cluster-wide task table (GCS task events)
  GET /api/task_summary      state->count + export-drop accounting
  GET /api/timeline          chrome://tracing trace of the task events
  GET /api/trace/<trace_id>  one request's span tree + latency waterfall
  GET /api/trace_summary     per-hop p50/p95 attribution over all traces
  GET /api/health            GCS failure-detection stats (health_stats)
  GET /api/stacks            live all-thread stacks from every cluster
                             process (?node=<prefix> targets one node)
  GET /api/profile           continuous-profiling summary over the GCS
                             profile table (?node=&since=&top=); add
                             &format=speedscope|collapsed for a raw
                             flamegraph export
  GET /api/logs              per-worker log files per node (?node=);
                             ?node=<prefix>&file=<name>[&lines=N] tails
  GET /metrics               Prometheus/OpenMetrics text exposition
                             (system gauges + internal ray_tpu_internal_*
                             incl. the GCS-side health series + user
                             metrics); ?format=json for the same series
                             as a JSON document
  GET /api/metrics_range     time-series reads over the GCS metrics table
                             (?name=&op=range|rate|quantile|series&tags=
                             k=v,...&node=&since=&until=&window=&q=&limit=)
  GET /api/alerts            firing alerts + transition log from the GCS
                             rule engine (?state=firing|resolved&limit=)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ray_tpu.core.gcs import GcsClient

__all__ = ["DashboardHead"]


def _prom_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class DashboardHead:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        self._gcs = GcsClient(gcs_address)
        self._gcs_address = gcs_address
        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                try:
                    parsed = urlparse(self.path)
                    query = {k: v[0] for k, v in
                             parse_qs(parsed.query).items()}
                    body, ctype = dash._route(parsed.path, query)
                except KeyError:
                    self.send_error(404)
                    return
                except ValueError as e:
                    # malformed query parameter (?lines=foo): caller error
                    self.send_error(400, str(e))
                    return
                except Exception as e:  # noqa: BLE001
                    self.send_error(500, str(e))
                    return
                data = body.encode() if isinstance(body, str) else body
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dashboard", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------- routing

    def _route(self, path: str, query: Optional[dict] = None):
        query = query or {}
        if path == "/":
            return self._index(), "text/html"
        if path == "/metrics":
            if query.get("format") == "json":
                return (json.dumps(self._metrics_json(), default=str),
                        "application/json")
            return self._metrics(), "text/plain; version=0.0.4"
        if path == "/api/metrics_range":
            return (json.dumps(self._metrics_range(query), default=str),
                    "application/json")
        if path == "/api/alerts":
            return (json.dumps(self._alerts(query), default=str),
                    "application/json")
        if path == "/api/stacks":
            return (json.dumps(self._stacks(query), default=str),
                    "application/json")
        if path == "/api/profile":
            body = self._profile(query)
            if isinstance(body, str):  # collapsed text export
                return body, "text/plain"
            return json.dumps(body, default=str), "application/json"
        if path == "/api/logs":
            body = self._logs(query)
            if isinstance(body, str):  # tail text
                return body, "text/plain"
            return json.dumps(body, default=str), "application/json"
        api = {
            "/api/nodes": self._nodes,
            "/api/actors": self._actors,
            "/api/jobs": self._jobs,
            "/api/cluster_resources": self._cluster_resources,
            "/api/load": self._load,
            "/api/placement_groups": self._pgs,
            "/api/tasks": self._tasks,
            "/api/task_summary": self._task_summary,
            "/api/timeline": self._timeline,
            "/api/trace_summary": self._trace_summary,
            "/api/health": self._health,
        }
        if path in api:
            return json.dumps(api[path](), default=str), "application/json"
        if path.startswith("/api/trace/"):
            trace_id = path[len("/api/trace/"):]
            if not trace_id:
                raise KeyError(path)
            return (json.dumps(self._trace(trace_id), default=str),
                    "application/json")
        if path.startswith("/api/jobs/") and path.endswith("/logs"):
            job_id = path[len("/api/jobs/"):-len("/logs")]
            raw = self._gcs.kv_get("jobs", (job_id + "/logs").encode())
            if raw is None:
                raise KeyError(path)
            return raw, "text/plain"
        raise KeyError(path)

    # ------------------------------------------------------------- sources

    def _nodes(self):
        return self._gcs.nodes()

    def _actors(self):
        return self._gcs.list_actors()

    def _jobs(self):
        out = []
        for key in self._gcs.kv_keys("jobs", b""):
            if key.endswith(b"/logs"):
                continue
            raw = self._gcs.kv_get("jobs", key)
            if raw:
                out.append(json.loads(raw))
        return out

    def _cluster_resources(self):
        total: dict = {}
        avail: dict = {}
        for n in self._gcs.nodes():
            if not n["alive"]:
                continue
            for k, v in n["resources_total"].items():
                total[k] = total.get(k, 0.0) + v
            for k, v in n.get("resources_available", {}).items():
                avail[k] = avail.get(k, 0.0) + v
        return {"total": total, "available": avail}

    def _load(self):
        return self._gcs.load_metrics()

    def _pgs(self):
        return self._gcs.state_snapshot().get("placement_groups", [])

    def _tasks(self):
        """Latest state per task, cluster-wide (GCS task-event table —
        raylets batch-flush their lifecycle events there)."""
        return self._gcs.list_task_events()

    def _task_summary(self):
        return self._gcs.summarize_task_events()

    def _timeline(self):
        from ray_tpu.util.state import build_timeline

        return build_timeline(self._gcs.task_events_raw())

    def _trace(self, trace_id: str):
        """One request's reassembled span tree + critical-path waterfall
        (GCS trace table — every process batch-flushes its spans there)."""
        from ray_tpu.util import trace_analysis

        spans = self._gcs.get_trace(trace_id)
        return {
            "trace_id": trace_id,
            "num_spans": len(spans),
            "tree": trace_analysis.build_tree(spans),
            "critical_path": trace_analysis.critical_path(spans),
        }

    def _trace_summary(self):
        from ray_tpu.util import trace_analysis

        out = trace_analysis.aggregate(self._gcs.list_trace_spans())
        out["table"] = self._gcs.trace_table_stats()
        return out

    def _health(self):
        """Failure-detection observability (suspicions, fencing, drains,
        time-to-detect) straight from the GCS health monitor."""
        return self._gcs.health_stats()

    def _stacks(self, query: dict):
        """Live all-thread stacks, cluster-wide (or one node with
        ?node=<prefix>) — the GCS relays a targeted query to each raylet,
        which dumps itself and its workers (see ``ray_tpu stack``)."""
        return self._gcs.collect_stacks(
            node_id=query.get("node"),
            timeout_s=float(query.get("timeout", 3.0)))

    def _profile(self, query: dict):
        """Continuous-profiling readout over the GCS profile table:
        the per-function summary by default; ?format=speedscope returns
        a loadable speedscope document, ?format=collapsed flamegraph.pl
        text."""
        from ray_tpu.util import profiling

        samples = self._gcs.list_profile_samples(
            node_id=query.get("node"),
            since=float(query.get("since", 0.0)),
            limit=int(query.get("limit", 100000)))
        fmt = query.get("format")
        if fmt == "speedscope":
            return profiling.to_speedscope(samples)
        if fmt == "collapsed":
            return profiling.to_collapsed(samples)
        out = profiling.summarize(samples,
                                  top=int(query.get("top", 30)))
        out["table"] = self._gcs.profile_table_stats()
        return out

    def _logs(self, query: dict):
        """Worker log files: the per-node listing, or — with ?node= and
        ?file= — that file's tail as plain text."""
        name = query.get("file")
        if name:
            out = self._gcs.node_query(
                query.get("node"), "logs",
                {"action": "tail", "name": name,
                 "lines": int(query.get("lines", 100))},
                timeout_s=float(query.get("timeout", 3.0)))
            hits = [rep for _nid, rep in
                    sorted(out.get("reports", {}).items())
                    if isinstance(rep, dict) and "data" in rep]
            if len(hits) > 1:
                # per-raylet sequence names repeat on every node: make
                # the caller disambiguate rather than guessing for them
                raise ValueError(
                    f"log file {name!r} exists on "
                    + ", ".join(r["node_id"][:12] for r in hits)
                    + " — pass ?node=<prefix>")
            if hits:
                return hits[0]["data"]
            raise KeyError(f"log file {name!r}")
        out = self._gcs.node_query(query.get("node"), "logs",
                                   {"action": "list"},
                                   timeout_s=float(query.get("timeout",
                                                             3.0)))
        return {nid: rep for nid, rep in out.get("reports", {}).items()
                if isinstance(rep, list)}

    def _metrics_range(self, query: dict):
        """Time-series reads over the GCS metrics table: range dumps the
        retained points, rate/quantile evaluate over ?window= seconds,
        series summarizes every retained series."""
        tags = None
        if query.get("tags"):
            tags = dict(kv.split("=", 1)
                        for kv in query["tags"].split(",") if "=" in kv)
        return self._gcs.query_metrics(
            name=query.get("name"),
            op=query.get("op", "range"),
            tags=tags,
            node_id=query.get("node"),
            since=float(query["since"]) if "since" in query else None,
            until=float(query["until"]) if "until" in query else None,
            window_s=float(query.get("window", 60.0)),
            q=float(query.get("q", 0.99)),
            limit=int(query.get("limit", 2000)))

    def _alerts(self, query: dict):
        """Firing alerts + the recent firing/resolved transition log from
        the GCS rule engine."""
        return self._gcs.list_alerts(state=query.get("state"),
                                     limit=int(query.get("limit", 100)))

    # ------------------------------------------------------------- metrics

    def _system_gauges(self):
        """The dashboard-computed cluster gauges (not in the metrics KV):
        alive nodes, per-node resources, actor-state counts."""
        nodes = self._gcs.nodes()
        alive = [n for n in nodes if n["alive"]]
        states: dict = {}
        for a in self._gcs.list_actors():
            st = a.get("state", "?")
            states[st] = states.get(st, 0) + 1
        return alive, states

    def _metrics_json(self):
        """The /metrics series as a JSON document (?format=json): system
        gauges plus every merged producer family."""
        from ray_tpu.util.metrics import kv_metrics_json, merge_kv_metrics

        alive, states = self._system_gauges()
        resources = [
            {"node": n["node_id"][:12],
             "total": n["resources_total"],
             "available": n.get("resources_available", {})}
            for n in alive]
        return {
            "nodes_alive": len(alive),
            "resources": resources,
            "actors": states,
            "metrics": kv_metrics_json(merge_kv_metrics(self._gcs)),
        }

    def _metrics(self) -> str:
        """Prometheus text exposition (reference: the per-node MetricsAgent
        re-export, `python/ray/_private/metrics_agent.py:375`).  System
        gauges from GCS state + any user metrics pushed to the GCS KV by
        ``ray_tpu.util.metrics``."""
        lines = []
        alive, states = self._system_gauges()
        lines.append("# HELP ray_tpu_nodes_alive Alive raylets in the "
                     "GCS node table.")
        lines.append("# TYPE ray_tpu_nodes_alive gauge")
        lines.append(f"ray_tpu_nodes_alive {len(alive)}")
        lines.append("# HELP ray_tpu_resource_total Per-node declared "
                     "resource capacity.")
        lines.append("# TYPE ray_tpu_resource_total gauge")
        lines.append("# HELP ray_tpu_resource_available Per-node "
                     "currently-unclaimed resources.")
        lines.append("# TYPE ray_tpu_resource_available gauge")
        for n in alive:
            nid = _prom_escape(n["node_id"][:12])
            for k, v in n["resources_total"].items():
                lines.append(
                    f'ray_tpu_resource_total{{node="{nid}",'
                    f'resource="{_prom_escape(k)}"}} {v}')
            for k, v in n.get("resources_available", {}).items():
                lines.append(
                    f'ray_tpu_resource_available{{node="{nid}",'
                    f'resource="{_prom_escape(k)}"}} {v}')
        lines.append("# HELP ray_tpu_actors Actor count per lifecycle "
                     "state.")
        lines.append("# TYPE ray_tpu_actors gauge")
        for st, count in sorted(states.items()):
            lines.append(f'ray_tpu_actors{{state="{_prom_escape(st)}"}} '
                         f'{count}')
        # User metrics: serialized samples under KV ns "metrics".
        try:
            from ray_tpu.util.metrics import render_kv_metrics

            lines.extend(render_kv_metrics(self._gcs))
        except ImportError:
            pass
        return "\n".join(lines) + "\n"

    # -------------------------------------------------------------- index

    def _index(self) -> str:
        import html as _html

        res = self._cluster_resources()
        nodes = self._nodes()
        jobs = self._jobs()
        rows = "".join(
            f"<tr><td>{_html.escape(n['node_id'][:12])}</td>"
            f"<td>{'ALIVE' if n['alive'] else 'DEAD'}</td>"
            f"<td>{_html.escape(json.dumps(n['resources_total']))}</td></tr>"
            for n in nodes)
        job_rows = "".join(
            f"<tr><td>{_html.escape(j['submission_id'])}</td>"
            f"<td>{_html.escape(j['status'])}</td>"
            f"<td><code>{_html.escape(j['entrypoint'][:80])}</code></td></tr>"
            for j in jobs)
        return f"""<!doctype html><html><head><title>ray_tpu dashboard</title>
<style>body{{font-family:sans-serif;margin:2em}}table{{border-collapse:
collapse}}td,th{{border:1px solid #ccc;padding:4px 8px}}</style></head>
<body><h1>ray_tpu</h1>
<p>GCS: <code>{self._gcs_address}</code></p>
<p>resources: <code>{json.dumps(res)}</code></p>
<h2>nodes</h2><table><tr><th>id</th><th>state</th><th>resources</th></tr>
{rows}</table>
<h2>jobs</h2><table><tr><th>id</th><th>status</th><th>entrypoint</th></tr>
{job_rows}</table>
<p>APIs: /api/nodes /api/actors /api/jobs /api/cluster_resources /api/load
/api/placement_groups /api/tasks /api/task_summary /api/timeline
/api/trace/&lt;id&gt; /api/trace_summary /api/health /api/stacks
/api/profile /api/logs /api/metrics_range /api/alerts /metrics</p>
</body></html>"""

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        try:
            self._gcs.close()
        except Exception:  # noqa: BLE001
            pass
