"""Fake multi-node cluster for tests — the ``ray.cluster_utils.Cluster``
analogue (`python/ray/cluster_utils.py:99`, ``add_node`` `:165`).

Spawns a real GCS server process and one raylet PROCESS per simulated node
on this machine, each with its own shm object store, worker pool, and TCP
listener — so scheduling spillback, cross-node object transfer, and node
failure (``remove_node`` kills the raylet with SIGKILL) exercise the same
code paths a physical cluster would.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


class NodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id: str, port: int,
                 resources: Dict[str, float], object_store_mb: int = 128):
        self.proc = proc
        self.node_id = node_id
        self.port = port
        self.resources = resources
        self.object_store_mb = object_store_mb

    def alive(self) -> bool:
        return self.proc.poll() is None


def _read_tagged_line(proc: subprocess.Popen, tag: str, timeout: float = 30.0):
    """Read stdout lines until one starts with ``tag`` (startup banner)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"process exited with {proc.returncode} before printing "
                    f"{tag!r}: {proc.stderr.read() if proc.stderr else ''}")
            time.sleep(0.01)
            continue
        line = line.strip()
        if line.startswith(tag):
            return line
    raise TimeoutError(f"timed out waiting for {tag!r} banner")


def make_cluster_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for spawned GCS/raylet processes: driver import path,
    fast failure detection for tests, CPU-only jax."""
    env = dict(os.environ)
    # Subprocesses must resolve ray_tpu (and the user's modules) no
    # matter their cwd — propagate the driver's import path, the same
    # way the raylet ships it to workers.
    path_entries = [p for p in sys.path if p] + [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    seen: set = set()
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in path_entries if not (p in seen or seen.add(p)))
    # Fast failure detection for tests (prod tunes these up).
    env.setdefault("RAY_TPU_GCS_HEARTBEAT_INTERVAL_S", "0.1")
    env.setdefault("RAY_TPU_GCS_NODE_TIMEOUT_S", "1.5")
    # Cluster workers are control-plane only in tests: never let them
    # grab the TPU chip or spend seconds importing jax eagerly.
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(extra or {})
    return env


def spawn_gcs(env: Dict[str, str], port: int = 0,
              persist: Optional[str] = None):
    """Start a GCS server process; returns ``(proc, address)``."""
    cmd = [sys.executable, "-m", "ray_tpu.core.gcs_main", "--port",
           str(port)]
    if persist:
        cmd += ["--persist", persist]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env)
    banner = _read_tagged_line(proc, "GCS_ADDRESS")
    return proc, banner.split()[1]


def spawn_raylet(gcs_address: str, resources: Dict[str, float],
                 object_store_mb: int, env: Dict[str, str]) -> NodeHandle:
    """Start one raylet process against ``gcs_address`` and wait for its
    startup banner."""
    import json

    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.raylet_main",
         "--gcs", gcs_address,
         "--resources", json.dumps(resources),
         "--store-mb", str(object_store_mb)],
        stdout=subprocess.PIPE, stderr=None,
        text=True, env=env)
    banner = _read_tagged_line(proc, "RAYLET")
    fields = dict(kv.split("=") for kv in banner.split()[1:])
    return NodeHandle(proc, fields["node_id"], int(fields["port"]),
                      dict(resources), object_store_mb=object_store_mb)


class Cluster:
    """Start with a head node, then ``add_node`` more; ``connect`` attaches
    the current process as a driver (``ray_tpu.init(address=...)``)."""

    def __init__(self, initialize_head: bool = True,
                 head_resources: Optional[Dict[str, float]] = None,
                 env: Optional[Dict[str, str]] = None,
                 gcs_persist_path: Optional[str] = None,
                 chaos_control_file: Optional[str] = None,
                 memory_usage_file: Optional[str] = None):
        """``gcs_persist_path``: enable GCS fault tolerance — durable
        tables snapshot there and ``restart_gcs()`` brings the control
        plane back on the SAME port (raylets need
        RAY_TPU_GCS_RECONNECT_TIMEOUT_S > 0 to ride through).

        ``chaos_control_file``: export this path as the chaos control file
        (``RAY_TPU_CHAOS_NET_PARTITION_FILE``) into every spawned
        GCS/raylet/worker, so a chaos driver steers partitions and
        slow-exec windows in live processes by rewriting one JSON file.

        ``memory_usage_file``: export as ``RAY_TPU_MEMORY_USAGE_FILE`` and
        enable the raylet memory monitor — the driver injects OOM
        pressure by writing a usage fraction into the file."""
        self._env = make_cluster_env(env)
        if chaos_control_file:
            self._env["RAY_TPU_CHAOS_NET_PARTITION_FILE"] = \
                chaos_control_file
        if memory_usage_file:
            self._env["RAY_TPU_MEMORY_USAGE_FILE"] = memory_usage_file
            self._env.setdefault("RAY_TPU_MEMORY_MONITOR_INTERVAL_S",
                                 "0.25")
        self._gcs_persist = gcs_persist_path
        self.nodes: List[NodeHandle] = []
        self._gcs_proc, self.address = spawn_gcs(
            self._env, persist=gcs_persist_path)
        self._connected = False
        if initialize_head:
            self.head_node = self.add_node(
                **(head_resources or {"num_cpus": 2}))

    def add_node(self, num_cpus: float = 2, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_mb: int = 128) -> NodeHandle:
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        handle = spawn_raylet(self.address, res, object_store_mb, self._env)
        self.nodes.append(handle)
        return handle

    def kill_gcs(self):
        """SIGKILL the GCS process (chaos; reference:
        `test_gcs_fault_tolerance.py`)."""
        if self._gcs_proc.poll() is None:
            self._gcs_proc.send_signal(signal.SIGKILL)
            self._gcs_proc.wait(timeout=10)

    def restart_gcs(self):
        """Restart the GCS on the SAME address from its persisted
        snapshot.  Requires gcs_persist_path."""
        assert self._gcs_persist, "Cluster(gcs_persist_path=...) required"
        self.kill_gcs()
        port = int(self.address.rsplit(":", 1)[1])
        deadline = time.monotonic() + 15
        last_err = None
        while time.monotonic() < deadline:
            try:
                self._gcs_proc, addr = spawn_gcs(
                    self._env, port=port, persist=self._gcs_persist)
                assert addr == self.address
                return
            except RuntimeError as e:  # port still in TIME_WAIT
                last_err = e
                time.sleep(0.3)
        raise RuntimeError(f"could not restart GCS: {last_err}")

    def replace_node(self, node: NodeHandle) -> NodeHandle:
        """SIGKILL ``node`` and respawn a replacement with the same
        resources and store size IN ITS SLOT (same index in ``nodes``), so
        chaos schedules addressing nodes by slot keep a stable mapping
        across kills.  Returns the replacement handle."""
        try:
            idx = self.nodes.index(node)
        except ValueError:
            idx = None
        self.remove_node(node)
        handle = spawn_raylet(self.address, dict(node.resources),
                              node.object_store_mb, self._env)
        if idx is None or idx >= len(self.nodes):
            self.nodes.append(handle)
        else:
            self.nodes.insert(idx, handle)
        if getattr(self, "head_node", None) is node:
            self.head_node = handle
        return handle

    def pause_node(self, node: NodeHandle):
        """SIGSTOP the raylet process — simulates a network partition /
        long stall: the node stops heartbeating and answering liveness
        probes while its sockets stay open, so the GCS suspicion machine
        declares it dead; ``resume_node`` then 'heals the partition' and
        the resurrected raylet learns it was fenced."""
        if node.alive():
            node.proc.send_signal(signal.SIGSTOP)

    def resume_node(self, node: NodeHandle):
        """SIGCONT a paused raylet (heal the simulated partition)."""
        if node.alive():
            node.proc.send_signal(signal.SIGCONT)

    def remove_node(self, node: NodeHandle, allow_graceful: bool = False):
        """SIGKILL by default — simulates node failure (reference:
        ``Cluster.remove_node`` / NodeKillerActor chaos tooling)."""
        if node.alive():
            node.proc.send_signal(
                signal.SIGTERM if allow_graceful else signal.SIGKILL)
            node.proc.wait(timeout=10)
        if node in self.nodes:
            self.nodes.remove(node)
        # A SIGKILLed raylet never unlinks its shm store segment; reap it
        # here so chaos runs don't bleed host memory (the runtime also
        # sweeps dead-pid segments on the next raylet start).
        import glob
        import shutil

        for path in glob.glob(f"/dev/shm/rt_store_{node.proc.pid}_*"):
            if path.endswith(".spill"):
                shutil.rmtree(path, ignore_errors=True)
            else:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def connect(self):
        import ray_tpu

        ray_tpu.init(address=self.address)
        self._connected = True
        return self

    def wait_for_nodes(self, count: Optional[int] = None, timeout: float = 10):
        """Block until GCS sees ``count`` (default: all started) alive nodes."""
        from ray_tpu.core.gcs import GcsClient

        want = count if count is not None else len(self.nodes)
        cli = GcsClient(self.address)
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                alive = [n for n in cli.nodes() if n["alive"]]
                if len(alive) >= want:
                    return True
                time.sleep(0.05)
            raise TimeoutError(
                f"only {len(alive)} of {want} nodes registered")
        finally:
            cli.close()

    def shutdown(self):
        import ray_tpu

        if self._connected:
            try:
                ray_tpu.shutdown()
            except Exception:  # noqa: BLE001
                pass
            self._connected = False
        for node in list(self.nodes):
            try:
                self.remove_node(node, allow_graceful=True)
            except Exception:  # noqa: BLE001
                try:
                    node.proc.kill()
                except OSError:
                    pass
        if self._gcs_proc.poll() is None:
            self._gcs_proc.terminate()
            try:
                self._gcs_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._gcs_proc.kill()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
