"""``ray_tpu`` CLI — cluster lifecycle + state inspection.

Reference analogue: `python/ray/scripts/scripts.py` (``ray start`` `:540`,
``ray stop`` `:1004`, ``ray status``).  argparse instead of click (no extra
dependency); run as ``python -m ray_tpu.scripts <command>``.

Commands:
  start --head [--port P] [--resources JSON]   start GCS + a raylet here
  start --address HOST:PORT [--resources JSON] join an existing cluster
  stop                                         stop local ray_tpu processes
  status --address HOST:PORT                   cluster resource summary
  list {nodes,actors,tasks} --address ...      state tables
  timeline --address ... --out FILE            chrome://tracing dump
  trace {export,summary} --address ...         request-flow traces:
                                               Perfetto export / per-hop
                                               latency attribution
  stack [target] --address ...                 live all-thread stacks from
                                               cluster processes (ray stack)
  profile {export,summary} --address ...       continuous profiling:
                                               speedscope/collapsed export,
                                               top-function table
  logs [file] --address ... [--follow]         list/tail per-worker log
                                               files (ray logs)
  metrics {query,top} --address ...            metric time-series:
                                               range/rate/quantile reads,
                                               busiest-series table
  alerts --address ... [--log]                 firing alerts + transitions
  chaos [--seed N] [--duration S] [--faults..] seeded compound-fault soak
                                               + invariant bank + MTTR
                                               report on a local cluster
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys

_PID_DIR = "/tmp/ray_tpu/pids"


def _save_pid(kind: str, pid: int):
    os.makedirs(_PID_DIR, exist_ok=True)
    with open(os.path.join(_PID_DIR, f"{kind}_{pid}.pid"), "w") as f:
        f.write(str(pid))


def cmd_start(args) -> int:
    resources = args.resources or "{}"
    if args.head:
        gcs = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.gcs_main",
             "--port", str(args.port)],
            stdout=subprocess.PIPE, text=True)
        line = gcs.stdout.readline().strip()
        address = line.split()[1]
        _save_pid("gcs", gcs.pid)
        print(f"GCS started at {address}")
    else:
        if not args.address:
            print("error: --address required without --head",
                  file=sys.stderr)
            return 2
        address = args.address
    raylet = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.raylet_main",
         "--gcs", address, "--resources", resources],
        stdout=subprocess.PIPE, text=True)
    line = raylet.stdout.readline().strip()
    _save_pid("raylet", raylet.pid)
    print(f"raylet started: {line}")
    print(f"\nconnect with: ray_tpu.init(address=\"{address}\")")
    return 0


def cmd_stop(args) -> int:
    stopped = 0
    if os.path.isdir(_PID_DIR):
        for name in os.listdir(_PID_DIR):
            path = os.path.join(_PID_DIR, name)
            try:
                with open(path) as f:
                    pid = int(f.read().strip())
                os.kill(pid, signal.SIGTERM)
                stopped += 1
            except (OSError, ValueError):
                pass
            os.unlink(path)
    print(f"stopped {stopped} process(es)")
    return 0


def _connect(args):
    import ray_tpu

    ray_tpu.init(address=args.address)
    return ray_tpu


def cmd_status(args) -> int:
    ray_tpu = _connect(args)
    nodes = ray_tpu.nodes()
    alive = [n for n in nodes if n["Alive"]]
    print(f"nodes: {len(alive)} alive / {len(nodes)} total")
    total = ray_tpu.cluster_resources()
    print("resources:", json.dumps(total))
    for n in nodes:
        mark = "+" if n["Alive"] else "-"
        print(f"  {mark} {n['NodeID'][:12]} {n.get('Hostname','')} "
              f"{json.dumps(n['Resources'])}")
    return 0


def cmd_drain(args) -> int:
    """Gracefully drain a node out of the cluster (reference: the
    autoscaler's DrainNode RPC): placement stops immediately, sole-copy
    store objects migrate to surviving nodes, checkpointable actors
    checkpoint-and-relocate, running tasks get up to the deadline — then
    the node retires with ZERO reconstructions."""
    import time as _time

    from ray_tpu.core.gcs import GcsClient

    cli = GcsClient(args.address)
    try:
        node_id = args.node_id
        matches = [n["node_id"] for n in cli.nodes()
                   if n["alive"] and n["node_id"].startswith(node_id)]
        if len(matches) != 1:
            print(f"error: node id prefix {node_id!r} matches "
                  f"{len(matches)} alive node(s)", file=sys.stderr)
            return 2
        node_id = matches[0]
        if not cli.drain_node(node_id, timeout_s=args.timeout):
            print(f"error: node {node_id} unknown or already dead",
                  file=sys.stderr)
            return 1
        print(f"draining {node_id} (deadline {args.timeout:.0f}s)")
        if args.no_wait:
            return 0
        deadline = _time.monotonic() + args.timeout + 10.0
        while _time.monotonic() < deadline:
            status = cli.drain_status(node_id)
            if status.get("state") == "drained":
                print(f"drained: {json.dumps(status)}")
                return 0
            _time.sleep(0.5)
        print("drain did not complete in time", file=sys.stderr)
        return 1
    finally:
        cli.close()


def cmd_list(args) -> int:
    _connect(args)
    from ray_tpu.util import state

    fn = {"nodes": state.list_nodes, "actors": state.list_actors,
          "tasks": state.list_tasks}[args.what]
    for row in fn():
        print(json.dumps(row, default=str))
    return 0


def cmd_tasks(args) -> int:
    """Cluster-wide task table from the GCS task-event export
    (reference: ``ray list tasks``)."""
    _connect(args)
    from ray_tpu.util import state

    for row in state.list_tasks(state=args.state, limit=args.limit):
        print(json.dumps(row, default=str))
    return 0


def cmd_task_summary(args) -> int:
    """State -> count over every job's tasks, plus export-drop and
    node-coverage accounting (reference: ``ray summary tasks``)."""
    _connect(args)
    from ray_tpu.util import state

    print(json.dumps(state.task_events_summary(), indent=1, default=str))
    return 0


_CLUSTER_DIR = "/tmp/ray_tpu/clusters"


def cmd_up(args) -> int:
    """Launch a cluster from a YAML config (reference: ``ray up``,
    `scripts.py:1238`): GCS + head raylet + autoscaler in one supervised
    head process; workers come and go via the autoscaler."""
    import yaml

    with open(args.config) as f:
        name = (yaml.safe_load(f) or {}).get("cluster_name", "default")
    os.makedirs(_CLUSTER_DIR, exist_ok=True)
    # Detach: the monitor must not hold the CLI's stdio (callers capturing
    # this command's output would otherwise wait on the long-lived child).
    log = open(os.path.join(_CLUSTER_DIR, f"{name}.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.autoscaler.monitor_main",
         "--config", os.path.abspath(args.config)],
        stdout=subprocess.PIPE, stderr=log, stdin=subprocess.DEVNULL,
        start_new_session=True, text=True)
    log.close()
    address = None
    for _ in range(600):
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("CLUSTER_ADDRESS"):
            address = line.split()[1]
            break
    if address is None:
        print("cluster failed to start", file=sys.stderr)
        return 1
    proc.stdout.close()  # monitor keeps running detached
    with open(os.path.join(_CLUSTER_DIR, f"{name}.json"), "w") as f:
        json.dump({"name": name, "pid": proc.pid, "address": address}, f)
    print(f"cluster {name!r} up at {address}")
    print(f"connect with: ray_tpu.init(address=\"{address}\")")
    print(f"tear down with: ray_tpu down --name {name}")
    return 0


def cmd_down(args) -> int:
    """Tear down a cluster started with ``up`` (reference: ``ray down``)."""
    path = os.path.join(_CLUSTER_DIR, f"{args.name}.json")
    try:
        with open(path) as f:
            rec = json.load(f)
    except OSError:
        print(f"no cluster record {args.name!r}", file=sys.stderr)
        return 1
    try:
        os.kill(rec["pid"], signal.SIGTERM)
    except ProcessLookupError:
        pass
    os.unlink(path)
    print(f"cluster {args.name!r} down")
    return 0


def cmd_memory(args) -> int:
    """Object-store usage + object table (reference: ``ray memory``)."""
    ray_tpu = _connect(args)
    from ray_tpu.util import state

    summary = state.summarize_objects()
    print(json.dumps(summary, indent=1, default=str))
    if args.verbose:
        for row in state.list_objects(limit=args.limit):
            print(json.dumps(row, default=str))
    return 0


def cmd_job(args) -> int:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address)
    if args.job_command == "submit":
        import shlex

        # One token = a pre-quoted shell command string (Ray-style
        # `job submit -- "python train.py --lr 1e-3"`): pass through
        # verbatim.  Multiple tokens = argv, re-quoted to survive the
        # supervisor's shell=True.
        if len(args.entrypoint) == 1:
            entrypoint = args.entrypoint[0]
        else:
            entrypoint = shlex.join(args.entrypoint)
        job_id = client.submit_job(
            entrypoint=entrypoint, submission_id=args.submission_id)
        print(f"submitted {job_id}")
        if not args.no_wait:
            for chunk in client.tail_job_logs(job_id):
                sys.stdout.write(chunk)
                sys.stdout.flush()
            status = client.get_job_status(job_id)
            print(f"job {job_id}: {status}")
            return 0 if status == "SUCCEEDED" else 1
    elif args.job_command == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_command == "logs":
        sys.stdout.write(client.get_job_logs(args.job_id))
    elif args.job_command == "stop":
        ok = client.stop_job(args.job_id)
        print("stopped" if ok else "not running")
    elif args.job_command == "list":
        for job in client.list_jobs():
            print(f"{job['submission_id']}\t{job['status']}\t"
                  f"{job['entrypoint'][:60]}")
    return 0


def cmd_serve(args) -> int:
    """Serve control (reference: `serve deploy/status/shutdown` CLI)."""
    _connect(args)
    from ray_tpu import serve

    if args.serve_command == "deploy":
        serve.start()
        apps = serve.run_config(args.config)
        print(f"deployed {len(apps)} application(s); "
              f"http port {serve.http_port()}")
    elif args.serve_command == "status":
        print(json.dumps(serve.status(), indent=1, default=str))
    elif args.serve_command == "shutdown":
        serve.shutdown()
        print("serve shut down")
    return 0


def cmd_timeline(args) -> int:
    ray_tpu = _connect(args)
    events = ray_tpu.timeline(args.out)
    print(f"wrote {len(events)} events to {args.out}")
    return 0


def cmd_trace(args) -> int:
    """Request-flow traces (GCS trace table): ``export`` writes
    Perfetto/chrome://tracing JSON (one trace with --trace-id, else every
    retained span); ``summary`` prints the per-hop "where do the
    microseconds go" attribution table."""
    _connect(args)
    from ray_tpu.util import state

    if args.action == "export":
        n = state.export_trace(args.out, trace_id=args.trace_id,
                               job_id=args.job, limit=args.limit)
        print(f"wrote {n} events to {args.out}")
        return 0
    summary = state.trace_summary(job_id=args.job, limit=args.limit)
    table = summary.get("table", {})
    print(f"traces: {summary['requests']} ({summary['errored']} errored)  "
          f"spans retained: {table.get('num_spans', 0)}  "
          f"dropped: {table.get('num_dropped', 0)}")
    print(f"e2e latency: p50 {summary['e2e_p50_us']}us  "
          f"p95 {summary['e2e_p95_us']}us")
    print(f"{'hop':<28}{'reqs':>7}{'p50_us':>10}{'p95_us':>10}"
          f"{'total_us':>12}{'share':>8}")
    for hop, row in summary["by_hop"].items():
        print(f"{hop:<28}{row['requests']:>7}{row['p50_us']:>10}"
              f"{row['p95_us']:>10}{row['total_us']:>12}"
              f"{row['share']:>8.1%}")
    return 0


def cmd_stack(args) -> int:
    """Live all-thread stacks from running cluster processes (reference:
    ``ray stack`` / the dashboard's py-spy dump, served in-process over
    the protocol — works on remote nodes and busy/deadlocked workers)."""
    _connect(args)
    from ray_tpu.util import profiling, state

    out = state.list_stacks(target=args.target, timeout_s=args.timeout)
    shown = 0
    for nid, procs in sorted(out.get("nodes", {}).items()):
        for p in procs or []:
            shown += 1
            actor = f" actor={p['actor_id'][:12]}" if p.get("actor_id") \
                else ""
            print(f"== node {nid[:12]} pid={p['pid']} "
                  f"({p['proc']}{actor}) ==")
            print(profiling.format_stacks(p.get("threads") or []))
    for p in out.get("gcs") or []:
        shown += 1
        print(f"== gcs pid={p['pid']} ==")
        print(profiling.format_stacks(p.get("threads") or []))
    if out.get("missing"):
        print(f"no report from {len(out['missing'])} node(s): "
              + " ".join(n[:12] for n in out["missing"]), file=sys.stderr)
    if not shown:
        print("no processes matched", file=sys.stderr)
        return 1
    return 0


def cmd_profile(args) -> int:
    """Continuous-profiling surfaces (GCS profile table): ``export``
    writes a speedscope JSON (or flamegraph.pl collapsed text) of the
    retained folded samples; ``summary`` prints the per-function "where
    does the CPU go" table."""
    _connect(args)
    from ray_tpu.util import state

    since = args.since if args.since is not None else 0.0
    if args.action == "export":
        n = state.export_profile(args.out, fmt=args.format,
                                 node_id=args.node, since=since,
                                 limit=args.limit)
        print(f"wrote {n} sample records to {args.out} ({args.format})")
        return 0
    summary = state.profile_summary(node_id=args.node, since=since,
                                    limit=args.limit, top=args.top)
    table = summary.get("table", {})
    print(f"samples: {summary['total_samples']} "
          f"({summary['num_records']} records, "
          f"{table.get('num_dropped', 0)} dropped)  "
          f"by_proc: {json.dumps(summary['by_proc'])}")
    print(f"{'frame':<52}{'self':>8}{'share':>8}")
    for row in summary["top_self"]:
        print(f"{row['frame'][:50]:<52}{row['samples']:>8}"
              f"{row['share']:>8.1%}")
    return 0


def cmd_metrics(args) -> int:
    """Metric time-series (GCS metrics table): ``query`` runs a range /
    rate / quantile read over one series; ``top`` prints the busiest
    series cluster-wide (rate-ranked summary)."""
    _connect(args)
    from ray_tpu.util import state

    if args.action == "top":
        out = state.query_metrics(op="series", window_s=args.window,
                                  limit=args.limit)
        series = (out or {}).get("series", [])[:args.top]
        if not series:
            print("no metric points retained", file=sys.stderr)
            return 1
        print(f"{'series':<64}{'kind':<11}{'rate/s':>10}{'value':>12}")
        for row in series:
            tags = ",".join(f"{k}={v}" for k, v in row.get("tags", []))
            label = row["name"].replace("ray_tpu_internal_", "")
            if tags:
                label += "{" + tags + "}"
            rate = row.get("rate")
            val = row.get("value", row.get("total"))
            p99 = row.get("p99")
            extra = f"  p99={p99:.4f}" if p99 is not None else ""
            print(f"{label[:62]:<64}{row['kind']:<11}"
                  f"{(f'{rate:.2f}' if rate is not None else '-'):>10}"
                  f"{(f'{val:.4g}' if val is not None else '-'):>12}"
                  f"{extra}")
        return 0
    tags = dict(kv.split("=", 1) for kv in (args.tag or []))
    out = state.query_metrics(
        name=args.name, op=args.op, tags=tags or None, node_id=args.node,
        since=args.since, until=args.until, window_s=args.window,
        q=args.q, limit=args.limit)
    if out is None:
        print("error: no cluster (metrics table needs a GCS)",
              file=sys.stderr)
        return 1
    if args.op == "range":
        for p in out.get("points", []):
            print(json.dumps(p, default=str))
        if out.get("truncated"):
            print(f"(truncated to {args.limit} points)", file=sys.stderr)
    else:
        print(json.dumps(out, default=str))
    return 0


def cmd_alerts(args) -> int:
    """Alert table (GCS rule engine): firing alerts plus the recent
    transition log (firing -> resolved)."""
    _connect(args)
    from ray_tpu.util import state

    out = state.list_alerts(state=args.state, limit=args.limit)
    if out is None:
        print("error: no cluster (alerts need a GCS)", file=sys.stderr)
        return 1
    firing = out.get("firing", [])
    print(f"firing: {len(firing)}  (log dropped: "
          f"{out.get('num_dropped', 0)})")
    for a in firing:
        print(f"  [{a['severity']}] {a['rule']}  value={a['value']:.4g} "
              f"threshold={a['threshold']:.4g}  since={a['since']:.1f}")
        if a.get("summary"):
            print(f"      {a['summary']}")
    if args.log:
        print("log (newest first):")
        for a in out.get("log", []):
            print(f"  {a['ts']:.1f} {a['state']:<9} [{a['severity']}] "
                  f"{a['rule']}  value={a['value']:.4g}")
    return 0


def cmd_chaos(args) -> int:
    """Seeded compound-fault soak: spawn a disposable local cluster, run
    a deterministic fault timeline against live workloads, then run the
    invariant bank (``util.chaos_schedule``).  Exit 0 only if every
    invariant holds; the executed timeline (JSONL) replays a failing
    seed exactly via ``--replay``."""
    import tempfile

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import chaos
    from ray_tpu.util import chaos_schedule as cs

    faults = [f.strip() for f in args.faults.split(",") if f.strip()]
    for f in faults:
        if f not in cs.FAULT_KINDS:
            print(f"error: unknown fault {f!r} "
                  f"(choose from {', '.join(cs.FAULT_KINDS)})",
                  file=sys.stderr)
            return 2
    workdir = args.workdir or tempfile.mkdtemp(prefix="ray_tpu_chaos_")
    os.makedirs(workdir, exist_ok=True)
    if args.replay:
        events = cs.load_timeline(args.replay)
        print(f"replaying {len(events)} events from {args.replay}")
    else:
        events = cs.build_schedule(args.seed, args.duration,
                                   faults=faults, n_slots=args.nodes)
        print(f"seed {args.seed}: {len(events)} events over "
              f"{args.duration:.0f}s")
    plan_path = os.path.join(workdir, "timeline.jsonl")
    cs.write_timeline(events, plan_path)
    log_path = os.path.join(workdir, "events.jsonl")
    baseline = chaos.snapshot_host()
    control_file = os.path.join(workdir, "chaos_ctrl.json")
    memory_file = os.path.join(workdir, "mem_usage")
    cluster = Cluster(
        gcs_persist_path=os.path.join(workdir, "gcs_snapshot"),
        chaos_control_file=control_file,
        memory_usage_file=memory_file,
        env={"RAY_TPU_GCS_RECONNECT_TIMEOUT_S": "30"})
    try:
        # Worker slots carry a "chaos" resource so the workloads and the
        # MTTR probe land on killable nodes, never the quiet head.
        pin = {"chaos": 0.01}
        for _ in range(args.nodes):
            cluster.add_node(num_cpus=2, resources={"chaos": 4})
        cluster.connect()
        cluster.wait_for_nodes()
        workloads = [
            cs.TaskFanoutWorkload(placement_resources=pin),
            cs.ActorMarkerWorkload(os.path.join(workdir, "markers"),
                                   placement_resources=pin),
            cs.PutGetWorkload(placement_resources=pin),
        ]
        if args.serve:
            workloads.append(cs.ServeWorkload())
        runner = cs.ChaosRunner(cluster, events, workloads,
                                control_file=control_file,
                                memory_file=memory_file,
                                log_path=log_path,
                                probe_resources=pin)
        report = runner.run()
    finally:
        cluster.shutdown()
    host_check = {"name": "clean_host", "ok": True, "detail": ""}
    try:
        chaos.assert_clean_host(baseline)
        host_check["detail"] = "no leaked processes/shm"
    except chaos.HostLeakError as e:
        host_check["ok"] = False
        host_check["detail"] = str(e)
        report["ok"] = False
        report["violations"].append("clean_host")
    report["checks"].append(host_check)
    # Persist the verdict next to the timelines so CI can ship the whole
    # workdir as one artifact and a failing seed is replayable offline.
    report_path = os.path.join(workdir, "report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, default=str)
    print()
    print(cs.render_report(report))
    print(f"\n  timeline: {plan_path}\n  event log: {log_path}"
          f"\n  report: {report_path}")
    return 0 if report["ok"] else 1


def cmd_logs(args) -> int:
    """List / tail the per-worker log files each raylet writes under its
    ``session_dir/logs`` (reference: ``ray logs``).  With a file name the
    tail prints; ``--follow`` polls the returned offset like tail -f."""
    import time as _time

    _connect(args)
    from ray_tpu.util import state

    if not args.file:
        listing = state.list_logs(node_id=args.node,
                                  timeout_s=args.timeout)
        if not any(listing.values()):
            print("no worker log files (single-node runs share the "
                  "driver's stdio)", file=sys.stderr)
            return 1
        for nid, entries in sorted(listing.items()):
            print(f"== node {nid[:12]} ==")
            for e in entries:
                pid = f" pid={e['pid']}" if e.get("pid") else ""
                print(f"  {e['name']:<24}{e['size']:>10} bytes{pid}")
        return 0
    tail = state.tail_log(args.file, node_id=args.node, lines=args.lines,
                          timeout_s=args.timeout)
    if tail is None:
        print(f"error: no node serves log file {args.file!r}",
              file=sys.stderr)
        return 1
    if tail.get("ambiguous_nodes"):
        print(f"warning: {args.file!r} exists on "
              f"{len(tail['ambiguous_nodes'])} nodes "
              f"({' '.join(n[:12] for n in tail['ambiguous_nodes'])}); "
              f"showing {tail['node_id'][:12]} — pass --node to pick",
              file=sys.stderr)
    sys.stdout.write(tail["data"])
    sys.stdout.flush()
    if not args.follow:
        return 0
    node, offset = tail["node_id"], tail["offset"]
    while True:
        _time.sleep(0.5)
        tail = state.tail_log(args.file, node_id=node, offset=offset,
                              timeout_s=args.timeout)
        if tail is None:
            continue  # node busy/briefly unreachable: keep polling
        offset = tail["offset"]
        if tail["data"]:
            sys.stdout.write(tail["data"])
            sys.stdout.flush()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start cluster processes on this host")
    p.add_argument("--head", action="store_true")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--address", default=None, help="GCS host:port to join")
    p.add_argument("--resources", default=None, help='JSON, e.g. {"CPU":8}')
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop processes started here")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="cluster resource summary")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("drain", help="gracefully drain a node (migrate "
                                     "objects/actors, then retire it)")
    p.add_argument("node_id", help="node id (unique prefix accepted)")
    p.add_argument("--address", required=True)
    p.add_argument("--timeout", type=float, default=30.0,
                   help="drain deadline seconds (default 30)")
    p.add_argument("--no-wait", action="store_true",
                   help="start the drain and return immediately")
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser("list", help="state tables")
    p.add_argument("what", choices=["nodes", "actors", "tasks"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("tasks", help="cluster-wide task table (GCS "
                                     "task-event export)")
    p.add_argument("--address", required=True)
    p.add_argument("--state", default=None,
                   help="filter, e.g. FINISHED / FAILED / RUNNING")
    p.add_argument("--limit", type=int, default=1000)
    p.set_defaults(fn=cmd_tasks)

    p = sub.add_parser("task-summary",
                       help="task state counts + export-drop accounting")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_task_summary)

    p = sub.add_parser("up", help="launch a cluster from YAML (ray up)")
    p.add_argument("config")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down an up'd cluster (ray down)")
    p.add_argument("--name", default="default")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("memory", help="object store usage (ray memory)")
    p.add_argument("--address", required=True)
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--limit", type=int, default=100)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("job", help="job submission (reference: ray job ...)")
    jsub = p.add_subparsers(dest="job_command", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--address", required=True)
    js.add_argument("--submission-id", default=None)
    js.add_argument("--no-wait", action="store_true")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    js.set_defaults(fn=cmd_job)
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("--address", required=True)
        jp.add_argument("job_id")
        jp.set_defaults(fn=cmd_job)
    jl = jsub.add_parser("list")
    jl.add_argument("--address", required=True)
    jl.set_defaults(fn=cmd_job)

    p = sub.add_parser("serve", help="model serving control")
    ssub = p.add_subparsers(dest="serve_command", required=True)
    sd = ssub.add_parser("deploy")
    sd.add_argument("--address", required=True)
    sd.add_argument("config", help="YAML/JSON app config")
    sd.set_defaults(fn=cmd_serve)
    for name in ("status", "shutdown"):
        sp = ssub.add_parser(name)
        sp.add_argument("--address", required=True)
        sp.set_defaults(fn=cmd_serve)

    p = sub.add_parser("timeline", help="chrome://tracing dump")
    p.add_argument("--address", required=True)
    p.add_argument("--out", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "trace", help="request-flow traces: export Perfetto JSON / "
                      "per-hop latency summary")
    p.add_argument("action", choices=["export", "summary"])
    p.add_argument("--address", required=True)
    p.add_argument("--trace-id", default=None,
                   help="export just this trace (default: all retained)")
    p.add_argument("--job", default=None, help="filter by job id")
    p.add_argument("--limit", type=int, default=100000)
    p.add_argument("--out", default="trace.json")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "stack", help="live all-thread stacks from cluster processes "
                      "(ray stack)")
    p.add_argument("target", nargs="?", default=None,
                   help="node-id prefix, actor name, or actor-id prefix "
                        "(default: every process cluster-wide)")
    p.add_argument("--address", required=True)
    p.add_argument("--timeout", type=float, default=3.0)
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser(
        "profile", help="continuous profiling: speedscope/collapsed "
                        "export / top-function summary")
    p.add_argument("action", choices=["export", "summary"])
    p.add_argument("--address", required=True)
    p.add_argument("--node", default=None, help="node-id prefix filter")
    p.add_argument("--since", type=float, default=None,
                   help="only samples whose window ends at/after this "
                        "unix time")
    p.add_argument("--limit", type=int, default=100000)
    p.add_argument("--top", type=int, default=30)
    p.add_argument("--format", choices=["speedscope", "collapsed"],
                   default="speedscope")
    p.add_argument("--out", default="profile.speedscope.json")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser(
        "metrics", help="metric time-series: range/rate/quantile query / "
                        "busiest-series table")
    p.add_argument("action", choices=["query", "top"])
    p.add_argument("--address", required=True)
    p.add_argument("--name", default=None,
                   help="metric name (required for query)")
    p.add_argument("--op", choices=["range", "rate", "quantile"],
                   default="range")
    p.add_argument("--tag", action="append", default=None,
                   metavar="K=V", help="label filter (repeatable)")
    p.add_argument("--node", default=None, help="node-id prefix filter")
    p.add_argument("--since", type=float, default=None,
                   help="unix time lower bound (exclusive)")
    p.add_argument("--until", type=float, default=None,
                   help="unix time upper bound (inclusive)")
    p.add_argument("--window", type=float, default=60.0,
                   help="window seconds for rate/quantile/top")
    p.add_argument("--q", type=float, default=0.99,
                   help="quantile for --op quantile (default 0.99)")
    p.add_argument("--limit", type=int, default=2000)
    p.add_argument("--top", type=int, default=30,
                   help="rows for the top table")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "alerts", help="firing alerts + recent transitions (GCS rule "
                       "engine)")
    p.add_argument("--address", required=True)
    p.add_argument("--state", choices=["firing", "resolved"], default=None)
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--log", action="store_true",
                   help="also print the transition log")
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser(
        "chaos", help="seeded compound-fault soak on a disposable local "
                      "cluster: fault timeline + invariant bank + MTTR "
                      "report (nonzero exit on any violation)")
    p.add_argument("--seed", type=int, default=0,
                   help="schedule seed — same seed, same fault timeline")
    p.add_argument("--duration", type=float, default=60.0,
                   help="seconds of fault injection (soak runs longer: "
                        "quiesce + invariant checks follow)")
    p.add_argument("--faults", default=",".join(
        ("node_kill", "partition", "gcs_restart", "drain", "slow_exec")),
        help="comma-separated fault kinds to draw from")
    p.add_argument("--nodes", type=int, default=2,
                   help="worker nodes (= schedule target slots)")
    p.add_argument("--serve", action="store_true",
                   help="also run a small Serve app under fire")
    p.add_argument("--replay", default=None, metavar="JSONL",
                   help="replay a previously logged timeline instead of "
                        "building one from --seed")
    p.add_argument("--workdir", default=None,
                   help="where timelines/logs/markers go (default: a "
                        "fresh temp dir)")
    p.set_defaults(fn=cmd_chaos)

    p = sub.add_parser(
        "logs", help="list/tail per-worker log files (ray logs)")
    p.add_argument("file", nargs="?", default=None,
                   help="log file name to tail (default: list files)")
    p.add_argument("--address", required=True)
    p.add_argument("--node", default=None, help="node-id prefix")
    p.add_argument("--lines", type=int, default=100)
    p.add_argument("--follow", "-f", action="store_true",
                   help="poll for new lines like tail -f")
    p.add_argument("--timeout", type=float, default=3.0)
    p.set_defaults(fn=cmd_logs)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
