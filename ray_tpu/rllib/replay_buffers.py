"""Replay buffers: uniform ring buffer + proportional prioritized replay.

Reference analogue: `rllib/utils/replay_buffers/replay_buffer.py` and
`prioritized_replay_buffer.py` (segment-tree proportional sampling, PER
from Schaul et al. 2015).  TPU-first framing: buffers live host-side
(numpy) on the learner; sampled batches are handed to the jitted update as
device arrays — the buffer itself never touches the chip.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = ["ReplayBuffer", "PrioritizedReplayBuffer"]


class ReplayBuffer:
    """Uniform FIFO ring over dict-of-arrays transitions."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._storage: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next_idx = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        """Append a batch of transitions (every value shares axis-0 length).
        Returns the buffer indices written (used by PER add)."""
        keys = list(batch.keys())
        n = len(batch[keys[0]])
        if not self._storage:
            for k in keys:
                arr = np.asarray(batch[k])
                self._storage[k] = np.zeros((self.capacity,) + arr.shape[1:],
                                            arr.dtype)
        idx = (self._next_idx + np.arange(n)) % self.capacity
        for k in keys:
            self._storage[k][idx] = np.asarray(batch[k])[:len(idx)]
        self._next_idx = int((self._next_idx + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)
        return idx

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        out = {k: v[idx] for k, v in self._storage.items()}
        out["batch_indexes"] = idx
        return out


class _SumTree:
    """Flat-array binary segment tree: O(log n) update + prefix-sum query
    (reference: `rllib/execution/segment_tree.py`)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        size = 1
        while size < capacity:
            size *= 2
        self._leaf0 = size
        self._tree = np.zeros(2 * size, np.float64)

    def set(self, idx: np.ndarray, values: np.ndarray):
        pos = np.asarray(idx, np.int64) + self._leaf0
        self._tree[pos] = values
        pos = np.unique(pos // 2)
        while True:
            self._tree[pos] = self._tree[2 * pos] + self._tree[2 * pos + 1]
            if pos[0] == 1:
                break
            pos = np.unique(pos // 2)

    def total(self) -> float:
        return float(self._tree[1])

    def prefix_index(self, prefix: np.ndarray) -> np.ndarray:
        """For each prefix sum, the leaf index whose cumulative range
        contains it."""
        prefix = np.asarray(prefix, np.float64).copy()
        pos = np.ones(len(prefix), np.int64)
        while pos[0] < self._leaf0:
            left = 2 * pos
            left_sum = self._tree[left]
            go_right = prefix > left_sum
            prefix = np.where(go_right, prefix - left_sum, prefix)
            pos = np.where(go_right, left + 1, left)
        return pos - self._leaf0


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER: P(i) ∝ p_i^alpha; importance weights
    w_i = (N * P(i))^-beta / max w (reference:
    `rllib/utils/replay_buffers/prioritized_replay_buffer.py`)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed)
        assert alpha >= 0
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._tree = _SumTree(self.capacity)
        self._max_priority = 1.0

    def add(self, batch: Dict[str, np.ndarray],
            priorities: Optional[np.ndarray] = None) -> np.ndarray:
        idx = super().add(batch)
        if priorities is None:
            prios = np.full(len(idx), self._max_priority)
        else:
            prios = np.asarray(priorities, np.float64) + self.eps
            self._max_priority = max(self._max_priority, float(prios.max()))
        self._tree.set(idx, prios ** self.alpha)
        return idx

    def sample(self, batch_size: int,
               beta: Optional[float] = None) -> Dict[str, np.ndarray]:
        beta = self.beta if beta is None else beta
        total = self._tree.total()
        # stratified: one uniform draw per equal-mass segment
        seg = total / batch_size
        targets = (np.arange(batch_size) + self._rng.random(batch_size)) * seg
        idx = self._tree.prefix_index(targets)
        idx = np.clip(idx, 0, self._size - 1)
        mass = self._tree._tree[idx + self._tree._leaf0]
        probs = mass / max(total, 1e-12)
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-beta)
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._storage.items()}
        out["batch_indexes"] = idx
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray):
        prios = np.asarray(priorities, np.float64) + self.eps
        self._max_priority = max(self._max_priority, float(prios.max()))
        self._tree.set(np.asarray(idx, np.int64), prios ** self.alpha)
