"""Algorithm — the RLlib training driver, a Tune Trainable.

Reference analogue: `rllib/algorithms/algorithm.py:191` (``Algorithm``
is a Tune ``Trainable``; ``step`` :813 delegates to ``training_step``)
+ `rllib/evaluation/worker_set.py:80` (actor fan-out).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent config (reference: `rllib/algorithms/algorithm_config.py`)."""

    def __init__(self):
        self.env_creator = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_length = 64
        self.lr = 3e-4
        self.gamma = 0.99
        self.seed = 0
        self.runner_resources: Dict[str, float] = {"CPU": 1}

    # fluent setters (subset of the reference's sections)
    def environment(self, env_creator) -> "AlgorithmConfig":
        self.env_creator = env_creator
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_runner: Optional[int] = None,
                    rollout_length: Optional[int] = None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_runner is not None:
            self.num_envs_per_runner = num_envs_per_runner
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise TypeError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self):
        raise NotImplementedError("use a concrete config (e.g. PPOConfig)")

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items()}


class Algorithm(Trainable):
    """Drives EnvRunner actors + a local jitted learner.

    ``train()`` (inherited) calls ``step`` -> ``training_step`` and
    appends iteration bookkeeping, matching the reference layering.
    """

    _config_cls = AlgorithmConfig

    def __init__(self, config=None):
        if isinstance(config, AlgorithmConfig):
            self._algo_config = config
            config = config.to_dict()
        else:
            self._algo_config = None
        super().__init__(config or {})

    def setup(self, config: Dict[str, Any]):
        import ray_tpu

        cfg = self._algo_config
        if cfg is None:
            cfg = self._config_cls()
            for k, v in (config or {}).items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
        self.algo_config = cfg
        assert cfg.env_creator is not None, "config.environment(...) missing"
        res = dict(cfg.runner_resources)
        # Env runners are the CPU plane: pin their jax to the host backend
        # so N runner processes never contend for the learner's TPU chip
        # (SURVEY §7: CPU env actors feed the TPU learner).
        runner_cls = ray_tpu.remote(
            num_cpus=res.get("CPU", 1), max_restarts=1,
            runtime_env={"env_vars": {"JAX_PLATFORMS": "cpu"}},
        )(self.runner_class())
        self.env_runners = [
            runner_cls.remote(*self.runner_args(cfg, i),
                              **self.runner_kwargs())
            for i in range(cfg.num_env_runners)
        ]
        self._total_env_steps = 0
        self._episode_returns: List[float] = []
        self.build_learner()
        self.sync_weights()

    # ---- override points -----------------------------------------------

    def runner_class(self):
        """The rollout-actor class (multi-agent algorithms override)."""
        from ray_tpu.rllib.env_runner import EnvRunner

        return EnvRunner

    def runner_args(self, cfg, i: int) -> tuple:
        """Positional args for the i-th runner actor."""
        return (cfg.env_creator, cfg.num_envs_per_runner,
                cfg.rollout_length, None, cfg.seed + i)

    def runner_kwargs(self) -> Dict[str, Any]:
        """Extra EnvRunner kwargs (e.g. DQN's epsilon-greedy action_fn)."""
        return {}

    def build_learner(self):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_weights(self):
        raise NotImplementedError

    def set_weights(self, weights):
        raise NotImplementedError

    # ---- shared plumbing -----------------------------------------------

    def sync_weights(self):
        """Broadcast learner weights to all runners (reference:
        ``WorkerSet.sync_weights``)."""
        import ray_tpu

        w = self.get_weights()
        ray_tpu.get([r.set_weights.remote(w) for r in self.env_runners],
                    timeout=60)

    def synchronous_parallel_sample(self) -> List[dict]:
        """Reference: `rllib/execution/rollout_ops.py:21`."""
        import ray_tpu

        rollouts = ray_tpu.get(
            [r.sample.remote() for r in self.env_runners], timeout=300)
        for ro in rollouts:
            self._total_env_steps += ro["metrics"]["env_steps"]
            self._episode_returns.extend(
                ep[0] for ep in ro["metrics"]["episodes"])
        return rollouts

    def step(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        info = self.training_step()
        dt = time.perf_counter() - t0
        recent = self._episode_returns[-100:]
        out = {
            "episode_reward_mean": (sum(recent) / len(recent)
                                    if recent else float("nan")),
            "num_env_steps_sampled": self._total_env_steps,
            "env_steps_per_sec": (info.pop("_steps_this_iter", 0) / dt
                                  if dt > 0 else 0.0),
        }
        out.update(info)
        return out

    def save_checkpoint(self) -> Optional[dict]:
        return {"weights": self.get_weights(),
                "total_env_steps": self._total_env_steps}

    def load_checkpoint(self, data: dict):
        self.set_weights(data["weights"])
        self._total_env_steps = data.get("total_env_steps", 0)
        self.sync_weights()

    def cleanup(self):
        import ray_tpu

        for r in self.env_runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass

    def stop(self):
        self.cleanup()
