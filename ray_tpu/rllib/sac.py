"""SAC — soft actor-critic for continuous control.

Reference analogue: `rllib/algorithms/sac/sac.py` (twin Q, tanh-squashed
Gaussian policy, automatic entropy temperature).  TPU-first: the whole
update (twin-critic TD, reparameterized actor, alpha, polyak) jits to one
XLA program; rollouts stay on CPU EnvRunner actors via the same
``action_fn`` seam DQN uses (the continuous action array rides the
generic SampleBatch columns).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, NEXT_OBS, OBS, REWARDS,
)

__all__ = ["SACConfig", "SAC", "sac_action_fn"]

_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0


def _mlp_init(rng, sizes, out_dim, out_scale=0.01):
    import jax
    import jax.numpy as jnp

    params = {}
    keys = jax.random.split(rng, len(sizes))
    dims = list(sizes)
    for i in range(len(dims) - 1):
        scale = jnp.sqrt(2.0 / dims[i])
        params[f"fc_{i}"] = {
            "w": jax.random.normal(keys[i], (dims[i], dims[i + 1]),
                                   jnp.float32) * scale,
            "b": jnp.zeros((dims[i + 1],)),
        }
    params["out"] = {
        "w": jax.random.normal(keys[-1], (dims[-1], out_dim),
                               jnp.float32) * out_scale,
        "b": jnp.zeros((out_dim,)),
    }
    return params


def _mlp_apply(params, x):
    import jax.numpy as jnp

    i = 0
    while f"fc_{i}" in params:
        p = params[f"fc_{i}"]
        x = jnp.tanh(x @ p["w"] + p["b"])
        i += 1
    return x @ params["out"]["w"] + params["out"]["b"]


def init_sac_nets(rng, obs_dim: int, act_dim: int, hidden=(256, 256)):
    import jax

    ka, k1, k2 = jax.random.split(rng, 3)
    sizes = [obs_dim, *hidden]
    qsizes = [obs_dim + act_dim, *hidden]
    return {
        "actor": _mlp_init(ka, sizes, 2 * act_dim),
        "q1": _mlp_init(k1, qsizes, 1, out_scale=1.0),
        "q2": _mlp_init(k2, qsizes, 1, out_scale=1.0),
    }


def actor_dist(actor_params, obs):
    """-> (mean, log_std) of the pre-squash Gaussian."""
    import jax.numpy as jnp

    out = _mlp_apply(actor_params, obs.reshape(obs.shape[0], -1))
    mean, log_std = jnp.split(out, 2, axis=-1)
    return mean, jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)


def sample_squashed(actor_params, obs, key):
    """Reparameterized tanh-Gaussian sample -> (action in [-1,1], logp)."""
    import jax
    import jax.numpy as jnp

    mean, log_std = actor_dist(actor_params, obs)
    std = jnp.exp(log_std)
    z = mean + std * jax.random.normal(key, mean.shape)
    a = jnp.tanh(z)
    # logp with tanh change-of-variables (numerically stable form)
    logp_z = -0.5 * (((z - mean) / std) ** 2 + 2 * log_std
                     + jnp.log(2 * jnp.pi))
    correction = 2.0 * (jnp.log(2.0) - z - jax.nn.softplus(-2.0 * z))
    logp = jnp.sum(logp_z - correction, axis=-1)
    return a, logp


def sac_action_fn(weights, obs, key):
    """EnvRunner action seam: tanh-Gaussian sample scaled to the env's
    action range (low/high ride the weights payload)."""
    import jax.numpy as jnp

    a, logp = sample_squashed(weights["params"]["actor"],
                              obs.astype(jnp.float32), key)
    low, high = weights["act_low"], weights["act_high"]
    action = low + (a + 1.0) * 0.5 * (high - low)
    zeros = jnp.zeros(a.shape[0], jnp.float32)
    return action, logp, zeros


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.alpha_lr = 3e-4
        self.buffer_size = 100_000
        self.train_batch_size = 256
        self.learning_starts = 512
        self.num_updates_per_iter = 64
        self.tau = 0.005                 # polyak target coefficient
        self.target_entropy = None       # default: -act_dim
        self.hidden = (256, 256)

    def build(self) -> "SAC":
        return SAC(self)


class SAC(Algorithm):
    _config_cls = SACConfig

    def runner_kwargs(self) -> Dict[str, Any]:
        return {"action_fn": sac_action_fn, "store_next_obs": True}

    def build_learner(self):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.replay_buffers import ReplayBuffer

        cfg = self.algo_config
        env = cfg.env_creator()
        obs_dim = int(np.prod(env.observation_space.shape))
        space = env.action_space
        act_dim = int(np.prod(space.shape))
        self._act_low = np.asarray(space.low, np.float32).reshape(act_dim)
        self._act_high = np.asarray(space.high, np.float32).reshape(act_dim)
        env.close()

        self.params = init_sac_nets(
            jax.random.PRNGKey(cfg.seed), obs_dim, act_dim, cfg.hidden)
        self.target_params = jax.tree.map(
            jnp.copy, {"q1": self.params["q1"], "q2": self.params["q2"]})
        self.log_alpha = jnp.zeros(())
        self._opt = optax.adam(cfg.lr)
        self._alpha_opt = optax.adam(cfg.alpha_lr)
        self.opt_state = self._opt.init(self.params)
        self.alpha_opt_state = self._alpha_opt.init(self.log_alpha)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._rng = jax.random.PRNGKey(cfg.seed + 1)

        gamma, tau = cfg.gamma, cfg.tau
        target_entropy = (cfg.target_entropy
                          if cfg.target_entropy is not None else -act_dim)
        low = jnp.asarray(self._act_low)
        high = jnp.asarray(self._act_high)

        def q_apply(qp, obs, act):
            x = jnp.concatenate([obs.reshape(obs.shape[0], -1), act], -1)
            return _mlp_apply(qp, x)[..., 0]

        def update(params, target_params, log_alpha, opt_state,
                   alpha_opt_state, batch, key):
            obs = batch[OBS].astype(jnp.float32)
            nobs = batch[NEXT_OBS].astype(jnp.float32)
            # env-scale actions -> [-1, 1] (the squashed policy's range)
            act = (batch[ACTIONS] - low) / (high - low) * 2.0 - 1.0
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(log_alpha)

            # ---- critic target
            na, nlogp = sample_squashed(params["actor"], nobs, k1)
            qt = jnp.minimum(
                q_apply(target_params["q1"], nobs, na),
                q_apply(target_params["q2"], nobs, na))
            target = batch[REWARDS] + gamma * (1.0 - batch[DONES]) * (
                qt - alpha * nlogp)
            target = jax.lax.stop_gradient(target)

            def critic_loss(p):
                q1 = q_apply(p["q1"], obs, act)
                q2 = q_apply(p["q2"], obs, act)
                return (jnp.mean((q1 - target) ** 2)
                        + jnp.mean((q2 - target) ** 2))

            def actor_loss(p):
                a, logp = sample_squashed(p["actor"], obs, k2)
                q = jnp.minimum(q_apply(p["q1"], obs, a),
                                q_apply(p["q2"], obs, a))
                return jnp.mean(alpha * logp - q), logp

            c_loss, c_grads = jax.value_and_grad(critic_loss)(params)
            (a_loss, logp), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(params)
            # critic grads update q nets; actor grads update the actor only
            grads = {
                "actor": a_grads["actor"],
                "q1": c_grads["q1"],
                "q2": c_grads["q2"],
            }
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)

            # ---- temperature
            def alpha_loss_fn(la):
                return -jnp.mean(jnp.exp(la) * jax.lax.stop_gradient(
                    logp + target_entropy))

            al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(log_alpha)
            al_updates, alpha_opt_state = self._alpha_opt.update(
                al_grad, alpha_opt_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, al_updates)

            # ---- polyak targets
            target_params = jax.tree.map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                target_params, {"q1": params["q1"], "q2": params["q2"]})
            return (params, target_params, log_alpha, opt_state,
                    alpha_opt_state,
                    {"critic_loss": c_loss, "actor_loss": a_loss,
                     "alpha": alpha})

        self._update = jax.jit(update, donate_argnums=(0, 1, 3, 4))

    def get_weights(self):
        import jax

        return {"params": {"actor": jax.tree.map(np.asarray,
                                                 self.params["actor"])},
                "act_low": self._act_low, "act_high": self._act_high}

    def set_weights(self, weights):
        self.params["actor"] = weights["params"]["actor"]

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.algo_config
        rollouts = self.synchronous_parallel_sample()
        steps_this_iter = 0
        for ro in rollouts:
            b = ro["batch"]
            steps_this_iter += len(b[REWARDS])
            self.buffer.add({
                OBS: b[OBS], ACTIONS: b[ACTIONS], REWARDS: b[REWARDS],
                NEXT_OBS: b[NEXT_OBS], DONES: b[DONES],
            })

        metrics = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                sample = self.buffer.sample(cfg.train_batch_size)
                sample.pop("batch_indexes", None)
                self._rng, sub = jax.random.split(self._rng)
                (self.params, self.target_params, self.log_alpha,
                 self.opt_state, self.alpha_opt_state, metrics) = \
                    self._update(self.params, self.target_params,
                                 self.log_alpha, self.opt_state,
                                 self.alpha_opt_state, sample, sub)
        self.sync_weights()
        out = {k: float(v) for k, v in metrics.items()}
        out.update({"buffer_size": len(self.buffer),
                    "_steps_this_iter": steps_this_iter})
        return out

    def save_checkpoint(self):
        import jax

        return {"params": jax.tree.map(np.asarray, self.params),
                "target_params": jax.tree.map(np.asarray,
                                              self.target_params),
                "log_alpha": np.asarray(self.log_alpha),
                "opt_state": jax.tree.map(np.asarray, self.opt_state),
                "alpha_opt_state": jax.tree.map(np.asarray,
                                                self.alpha_opt_state),
                "total_env_steps": self._total_env_steps}

    def load_checkpoint(self, state):
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.target_params = jax.tree.map(jnp.asarray,
                                          state["target_params"])
        self.log_alpha = jnp.asarray(state["log_alpha"])
        if "opt_state" in state:
            self.opt_state = jax.tree.map(jnp.asarray, state["opt_state"])
            self.alpha_opt_state = jax.tree.map(
                jnp.asarray, state["alpha_opt_state"])
        self._total_env_steps = state.get("total_env_steps", 0)
        # the runners must roll out with the RESTORED actor, not whatever
        # they had before (base-class contract)
        self.sync_weights()
