"""Policy/value networks — pure-JAX MLPs.

Reference analogue: `rllib/models/catalog.py` + `rllib/core/rl_module/`
(the RLModule forward).  TPU-first: a functional init/apply pair the
learner jits end-to-end; no framework wrapper classes.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp


def init_mlp_policy(rng, obs_dim: int, num_actions: int,
                    hidden: Sequence[int] = (64, 64)) -> Dict[str, Any]:
    """Shared torso, categorical policy head + value head."""
    params = {}
    sizes = [obs_dim, *hidden]
    keys = jax.random.split(rng, len(hidden) + 2)
    for i in range(len(hidden)):
        k1, _ = jax.random.split(keys[i])
        scale = jnp.sqrt(2.0 / sizes[i])
        params[f"fc_{i}"] = {
            "w": jax.random.normal(k1, (sizes[i], sizes[i + 1]),
                                   jnp.float32) * scale,
            "b": jnp.zeros((sizes[i + 1],)),
        }
    params["pi"] = {
        "w": jax.random.normal(keys[-2], (sizes[-1], num_actions),
                               jnp.float32) * 0.01,
        "b": jnp.zeros((num_actions,)),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[-1], (sizes[-1], 1), jnp.float32) * 1.0,
        "b": jnp.zeros((1,)),
    }
    return params


def mlp_forward(params, obs):
    """obs (B, obs_dim) -> (logits (B, A), value (B,))."""
    x = obs
    i = 0
    while f"fc_{i}" in params:
        p = params[f"fc_{i}"]
        x = jnp.tanh(x @ p["w"] + p["b"])
        i += 1
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def sample_action(params, obs, key):
    """Returns (action, logp, value) for a batch of observations."""
    logits, value = mlp_forward(params, obs)
    action = jax.random.categorical(key, logits, axis=-1)
    logp = jax.nn.log_softmax(logits)[
        jnp.arange(action.shape[0]), action]
    return action, logp, value
