"""Policy/value networks — pure-JAX MLPs.

Reference analogue: `rllib/models/catalog.py` + `rllib/core/rl_module/`
(the RLModule forward).  TPU-first: a functional init/apply pair the
learner jits end-to-end; no framework wrapper classes.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp


def init_mlp_policy(rng, obs_dim: int, num_actions: int,
                    hidden: Sequence[int] = (64, 64)) -> Dict[str, Any]:
    """Shared torso, categorical policy head + value head."""
    params = {}
    sizes = [obs_dim, *hidden]
    keys = jax.random.split(rng, len(hidden) + 2)
    for i in range(len(hidden)):
        k1, _ = jax.random.split(keys[i])
        scale = jnp.sqrt(2.0 / sizes[i])
        params[f"fc_{i}"] = {
            "w": jax.random.normal(k1, (sizes[i], sizes[i + 1]),
                                   jnp.float32) * scale,
            "b": jnp.zeros((sizes[i + 1],)),
        }
    params["pi"] = {
        "w": jax.random.normal(keys[-2], (sizes[-1], num_actions),
                               jnp.float32) * 0.01,
        "b": jnp.zeros((num_actions,)),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[-1], (sizes[-1], 1), jnp.float32) * 1.0,
        "b": jnp.zeros((1,)),
    }
    return params


def mlp_forward(params, obs):
    """obs (B, ...) -> (logits (B, A), value (B,)); trailing dims flatten."""
    x = obs.reshape(obs.shape[0], -1)
    i = 0
    while f"fc_{i}" in params:
        p = params[f"fc_{i}"]
        x = jnp.tanh(x @ p["w"] + p["b"])
        i += 1
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def init_cnn_policy(rng, obs_shape, num_actions: int,
                    channels=(32, 64, 64), dense: int = 512):
    """Nature-CNN torso for pixel observations (reference:
    `rllib/models/torch/visionnet.py` / the Atari defaults in
    `rllib/models/catalog.py`): conv 8x8/4, 4x4/2, 3x3/1 -> dense ->
    categorical + value heads.  obs_shape = (H, W, C)."""
    H, W, C = obs_shape
    keys = jax.random.split(rng, 6)
    specs = [(8, 4, C, channels[0]), (4, 2, channels[0], channels[1]),
             (3, 1, channels[1], channels[2])]
    params = {}
    h, w = H, W
    for i, (k, s, cin, cout) in enumerate(specs):
        fan_in = k * k * cin
        params[f"conv_{i}"] = {
            "w": jax.random.normal(keys[i], (k, k, cin, cout), jnp.float32)
            * jnp.sqrt(2.0 / fan_in),
            "b": jnp.zeros((cout,)),
        }
        h = (h - k) // s + 1
        w = (w - k) // s + 1
    flat = h * w * channels[-1]
    params["fc"] = {
        "w": jax.random.normal(keys[3], (flat, dense), jnp.float32)
        * jnp.sqrt(2.0 / flat),
        "b": jnp.zeros((dense,)),
    }
    params["pi"] = {
        "w": jax.random.normal(keys[4], (dense, num_actions),
                               jnp.float32) * 0.01,
        "b": jnp.zeros((num_actions,)),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[5], (dense, 1), jnp.float32),
        "b": jnp.zeros((1,)),
    }
    return params


def cnn_forward(params, obs):
    """obs (B, H, W, C) uint8/float -> (logits, value).  bf16-friendly:
    convs lower to MXU convolutions on TPU."""
    x = obs.astype(jnp.float32)
    if obs.dtype == jnp.uint8:
        x = x / 255.0
    _stride_for_kernel = {8: 4, 4: 2, 3: 1}  # Nature-CNN pairings
    i = 0
    while f"conv_{i}" in params:
        p = params[f"conv_{i}"]
        s = _stride_for_kernel[p["w"].shape[0]]
        x = jax.lax.conv_general_dilated(
            x, p["w"], window_strides=(s, s), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + p["b"])
        i += 1
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc"]["w"] + params["fc"]["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def policy_forward(params, obs):
    """Dispatch on the param structure: CNN torso when conv layers are
    present, MLP otherwise."""
    if "conv_0" in params:
        return cnn_forward(params, obs)
    return mlp_forward(params, obs)


def sample_action(params, obs, key):
    """Returns (action, logp, value) for a batch of observations."""
    logits, value = policy_forward(params, obs)
    action = jax.random.categorical(key, logits, axis=-1)
    logp = jax.nn.log_softmax(logits)[
        jnp.arange(action.shape[0]), action]
    return action, logp, value
