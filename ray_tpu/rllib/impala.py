"""IMPALA — asynchronous actor-learner with V-trace off-policy correction.

Reference analogue: `rllib/algorithms/impala/impala.py:68` (async rollout
queue feeding a learner, `:552` training_step) and the V-trace math from
`rllib/algorithms/impala/vtrace_*.py` (Espeholt et al. 2018, re-derived
here from the paper's recurrence, not ported).

TPU-first shape: env runners sample CONTINUOUSLY — the learner never
blocks on the slowest runner; each training_step consumes whatever
rollouts are ready (re-issuing sample() on the freed runners immediately)
and runs ONE jitted V-trace update per gathered batch.  Stale-policy
drift between the behavior policy (runner weights) and the target policy
(learner weights) is exactly what the rho/c clipping corrects.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    SampleBatch,
)


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vtrace_rho_bar = 1.0
        self.vtrace_c_bar = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.hidden = (64, 64)
        self.cnn = False  # Nature-CNN torso for (H, W, C) pixel obs
        self.max_inflight_per_runner = 1
        # >1: data-parallel learner replicas (LearnerGroup) — each update's
        # batch shards across them and gradients allreduce-average
        # (reference: `rllib/core/learner/learner_group.py:61`)
        self.num_learners = 1

    def build(self) -> "Impala":
        return Impala(self)


def make_vtrace_fn():
    """Returns vtrace(target_logps, behavior_logps, rewards, dones, values,
    bootstrap, gamma, rho_bar, c_bar) -> (vs, pg_adv), all time-major
    (T, B).  Reverse lax.scan of the V-trace recurrence:

        vs_t = V(x_t) + dt_t + gamma_t * c_t * (vs_{t+1} - V(x_{t+1}))
        dt_t = rho_t (r_t + gamma_t V(x_{t+1}) - V(x_t))
    """
    import jax
    import jax.numpy as jnp

    def vtrace(target_logps, behavior_logps, rewards, dones, values,
               bootstrap, gamma, rho_bar, c_bar):
        rhos = jnp.exp(target_logps - behavior_logps)
        clipped_rho = jnp.minimum(rho_bar, rhos)
        clipped_c = jnp.minimum(c_bar, rhos)
        discounts = gamma * (1.0 - dones)
        next_values = jnp.concatenate(
            [values[1:], bootstrap[None]], axis=0)
        deltas = clipped_rho * (rewards + discounts * next_values - values)

        def body(carry, xs):
            delta_t, disc_t, c_t = xs
            carry = delta_t + disc_t * c_t * carry
            return carry, carry

        _, dvs = jax.lax.scan(
            body, jnp.zeros_like(bootstrap),
            (deltas, discounts, clipped_c), reverse=True)
        vs = values + dvs
        next_vs = jnp.concatenate([vs[1:], bootstrap[None]], axis=0)
        pg_adv = clipped_rho * (rewards + discounts * next_vs - values)
        return vs, pg_adv

    return vtrace


def _make_loss_fn(cfg: ImpalaConfig):
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import policy_forward

    vtrace = make_vtrace_fn()

    def loss_fn(params, batch):
        # batch arrays are time-major (T, B, ...)
        T, B = batch[REWARDS].shape
        obs = batch[OBS].reshape((T * B,) + batch[OBS].shape[2:])
        logits, values = policy_forward(params, obs)
        logits = logits.reshape(T, B, -1)
        values = values.reshape(T, B)
        logp_all = jax.nn.log_softmax(logits)
        target_logps = jnp.take_along_axis(
            logp_all, batch[ACTIONS][..., None], axis=-1)[..., 0]
        vs, pg_adv = vtrace(
            jax.lax.stop_gradient(target_logps), batch[LOGPS],
            batch[REWARDS], batch[DONES], jax.lax.stop_gradient(values),
            batch["bootstrap"], cfg.gamma, cfg.vtrace_rho_bar,
            cfg.vtrace_c_bar)
        pg_loss = -jnp.mean(target_logps * jax.lax.stop_gradient(pg_adv))
        vf_loss = 0.5 * jnp.mean(
            jnp.square(values - jax.lax.stop_gradient(vs)))
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = (pg_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    return loss_fn


def _make_grad_apply(cfg: ImpalaConfig, optimizer):
    """(grad_fn, apply_fn) split — the LearnerGroup replicas allreduce
    between the two; the local path composes them in one call."""
    import jax
    import jax.numpy as jnp

    loss_fn = _make_loss_fn(cfg)

    @jax.jit
    def grad_fn(params, batch):
        (_, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        if cfg.grad_clip:
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                 for g in jax.tree.leaves(grads)))
            scale = jnp.minimum(1.0, cfg.grad_clip / (gnorm + 1e-8))
            grads = jax.tree.map(lambda g: g * scale, grads)
        return grads, metrics

    @jax.jit
    def apply_fn(params, opt_state, grads):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state

    return grad_fn, apply_fn


def _init_params_and_opt(cfg: ImpalaConfig, obs_shape, num_actions):
    """ONE copy of the param/optimizer construction for the local learner
    AND the LearnerGroup replicas — their lockstep guarantee depends on
    byte-identical init."""
    import jax
    import optax

    from ray_tpu.rllib.models import init_cnn_policy, init_mlp_policy

    key = jax.random.PRNGKey(cfg.seed)
    if cfg.cnn:
        params = init_cnn_policy(key, obs_shape, num_actions)
    else:
        params = init_mlp_policy(
            key, int(np.prod(obs_shape)), num_actions, cfg.hidden)
    optimizer = optax.rmsprop(cfg.lr, decay=0.99, eps=0.1)
    return params, optimizer, optimizer.init(params)


def _make_update_fn(cfg: ImpalaConfig, optimizer):
    grad_fn, apply_fn = _make_grad_apply(cfg, optimizer)

    def update(params, opt_state, batch):
        grads, metrics = grad_fn(params, batch)
        params, opt_state = apply_fn(params, opt_state, grads)
        return params, opt_state, metrics

    return update


class Impala(Algorithm):
    _config_cls = ImpalaConfig

    def build_learner(self):
        cfg: ImpalaConfig = self.algo_config
        probe_env = cfg.env_creator()
        num_actions = int(probe_env.action_space.n)
        obs_shape = probe_env.observation_space.shape
        probe_env.close()
        self._params, self._optimizer, self._opt_state = \
            _init_params_and_opt(cfg, obs_shape, num_actions)
        self._update = _make_update_fn(cfg, self._optimizer)
        self._learner_group = None
        if cfg.num_learners > 1:
            from ray_tpu.rllib.learner_group import LearnerGroup

            # the replicas run the SAME init (same seed/optimizer) so they
            # start in lockstep with the single-learner path
            def factory(cfg=cfg, obs_shape=obs_shape,
                        num_actions=num_actions):
                params, opt, opt_state = _init_params_and_opt(
                    cfg, obs_shape, num_actions)
                grad_fn, apply_fn = _make_grad_apply(cfg, opt)
                return {"params": params, "opt_state": opt_state,
                        "grad_fn": grad_fn, "apply_fn": apply_fn}

            self._learner_group = LearnerGroup(factory, cfg.num_learners)
        self._inflight: Dict[Any, Any] = {}  # ref -> runner

    def get_weights(self):
        import jax

        if self._learner_group is not None:
            return self._learner_group.get_weights()
        return jax.tree.map(np.asarray, self._params)

    def set_weights(self, weights):
        self._params = weights
        if self._learner_group is not None:
            # checkpoint restore must reach the replicas, not just the
            # (unused-under-fanout) local copy
            self._learner_group.set_weights(weights)

    def _ensure_sampling(self):
        """Keep every runner busy (the async pipeline of the reference's
        rollout queue)."""
        busy = set(self._inflight.values())
        for r in self.env_runners:
            if r not in busy:
                self._inflight[r.sample.remote()] = r

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        cfg: ImpalaConfig = self.algo_config
        self._ensure_sampling()
        # consume whatever is ready (at least one)
        refs = list(self._inflight.keys())
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=120)
        # opportunistically grab more finished rollouts
        more, _ = ray_tpu.wait(
            [r for r in refs if r not in ready],
            num_returns=max(1, len(refs) - 1), timeout=0) \
            if len(refs) > 1 else ([], None)
        metrics = {}
        steps = 0
        for ref in list(ready) + list(more):
            runner = self._inflight.pop(ref)
            ro = ray_tpu.get(ref)
            self._total_env_steps += ro["metrics"]["env_steps"]
            self._episode_returns.extend(
                ep[0] for ep in ro["metrics"]["episodes"])
            b: SampleBatch = ro["batch"]
            T, B = ro["t_shape"]
            tm = {
                OBS: b[OBS].reshape((T, B) + b[OBS].shape[1:]),
                ACTIONS: b[ACTIONS].reshape(T, B),
                LOGPS: b[LOGPS].reshape(T, B),
                REWARDS: b[REWARDS].reshape(T, B).astype(np.float32),
                DONES: b[DONES].reshape(T, B).astype(np.float32),
                "bootstrap": ro["last_values"].astype(np.float32),
            }
            if self._learner_group is not None:
                # time-major arrays shard on the env axis (1); bootstrap
                # values are (B,) and shard on 0
                m = self._learner_group.update(
                    tm, axis_map={OBS: 1, ACTIONS: 1, LOGPS: 1,
                                  REWARDS: 1, DONES: 1, "bootstrap": 0})
            else:
                self._params, self._opt_state, m = self._update(
                    self._params, self._opt_state, tm)
            metrics = {k: float(v) for k, v in m.items()}
            steps += T * B
            # restart sampling on the freed runner with FRESH weights
            runner.set_weights.remote(self.get_weights())
            self._inflight[runner.sample.remote()] = runner
        metrics["_steps_this_iter"] = steps
        metrics["num_inflight"] = len(self._inflight)
        return metrics

    def cleanup(self):
        if self._learner_group is not None:
            self._learner_group.shutdown()
        super().cleanup()

    def synchronous_parallel_sample(self):  # not used by IMPALA
        raise NotImplementedError
