"""Multi-agent RL: the MultiAgentEnv contract, a multi-agent rollout
actor, and multi-agent PPO with per-policy sample batches.

Reference analogues: `rllib/env/multi_agent_env.py:1` (dict-keyed
obs/action/reward protocol with the "__all__" done key),
`rllib/evaluation/rollout_worker.py` (policy_mapping_fn routing agents to
policies), `rllib/policy/sample_batch.py:MultiAgentBatch`.

Scope: simultaneous-move envs (every agent acts every step — the common
cooperative/competitive matrix and gridworld cases).  Each policy gets
its own params/optimizer and its own time-major SampleBatch assembled
from the streams of all agents mapped to it; updates reuse PPO's jitted
minibatch-epoch program per policy.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.ppo import PPOConfig, _make_update_fn, compute_gae
from ray_tpu.rllib.sample_batch import (
    ACTIONS, ADVANTAGES, DONES, LOGPS, OBS, REWARDS, TARGETS, VALUES,
    SampleBatch,
)

__all__ = ["MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentPPO",
           "MultiAgentPPOConfig"]


class MultiAgentEnv:
    """Dict-keyed env protocol (reference: `rllib/env/multi_agent_env.py`).

    * ``agents``: list of agent ids (static for the episode).
    * ``reset() -> (obs_dict, info_dict)``
    * ``step(action_dict) -> (obs, rewards, terminateds, truncateds,
      infos)`` — dicts keyed by agent id; ``terminateds["__all__"]`` /
      ``truncateds["__all__"]`` end the episode for everyone.
    """

    agents: List[str] = []

    def reset(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    def close(self):
        pass


class MultiAgentEnvRunner:
    """Rollout actor for MultiAgentEnv: steps one env, batching each
    policy's agents through one jitted forward per step, and returns a
    per-POLICY time-major SampleBatch."""

    def __init__(self, env_creator, rollout_length: int,
                 policy_mapping_fn, seed: int = 0):
        import jax

        from ray_tpu.rllib.models import sample_action

        self._env: MultiAgentEnv = env_creator()
        self._T = rollout_length
        self._map = policy_mapping_fn
        self._agents = list(self._env.agents)
        # stable per-policy agent grouping (simultaneous-move assumption)
        self._groups: Dict[str, List[str]] = {}
        for a in self._agents:
            self._groups.setdefault(self._map(a), []).append(a)
        self._weights: Optional[Dict[str, Any]] = None
        self._key = jax.random.PRNGKey(seed)
        self._fwd = jax.jit(sample_action)
        obs, _ = self._env.reset()
        self._obs = obs
        self._ep_return = 0.0
        self._completed: list = []

    def set_weights(self, weights: Dict[str, Any]):
        self._weights = weights
        return True

    def sample(self) -> Dict[str, Any]:
        import jax

        assert self._weights is not None, "set_weights before sample"
        T = self._T
        bufs = {
            pid: {
                OBS: [], ACTIONS: [], LOGPS: [], VALUES: [],
                REWARDS: [], DONES: [],
            } for pid in self._groups
        }
        for _ in range(T):
            act_dict: Dict[str, Any] = {}
            step_rows: Dict[str, tuple] = {}
            for pid, agents in self._groups.items():
                obs_b = np.stack([np.asarray(self._obs[a], np.float32)
                                  for a in agents])
                self._key, sub = jax.random.split(self._key)
                a, logp, value = self._fwd(self._weights[pid], obs_b, sub)
                a = np.asarray(a)
                for i, ag in enumerate(agents):
                    act_dict[ag] = int(a[i])
                step_rows[pid] = (obs_b, a, np.asarray(logp),
                                  np.asarray(value))
            obs, rewards, terms, truncs, _ = self._env.step(act_dict)
            done = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            for pid, agents in self._groups.items():
                obs_b, a, logp, value = step_rows[pid]
                bufs[pid][OBS].append(obs_b)
                bufs[pid][ACTIONS].append(a)
                bufs[pid][LOGPS].append(logp)
                bufs[pid][VALUES].append(value)
                bufs[pid][REWARDS].append(np.asarray(
                    [rewards.get(ag, 0.0) for ag in agents], np.float32))
                bufs[pid][DONES].append(
                    np.full(len(agents), float(done), np.float32))
            self._ep_return += float(sum(rewards.values()))
            if done:
                self._completed.append((self._ep_return, 0))
                self._ep_return = 0.0
                obs, _ = self._env.reset()
            self._obs = obs

        batches: Dict[str, SampleBatch] = {}
        t_shapes: Dict[str, tuple] = {}
        last_values: Dict[str, np.ndarray] = {}
        env_steps = 0
        for pid, agents in self._groups.items():
            B = len(agents)
            cols = {k: np.stack(v) for k, v in bufs[pid].items()}  # (T,B,..)
            obs_b = np.stack([np.asarray(self._obs[a], np.float32)
                              for a in agents])
            self._key, sub = jax.random.split(self._key)
            _, _, last_v = self._fwd(self._weights[pid], obs_b, sub)
            batches[pid] = SampleBatch({
                k: v.reshape((T * B,) + v.shape[2:]) for k, v in cols.items()
            })
            t_shapes[pid] = (T, B)
            last_values[pid] = np.asarray(last_v, np.float32)
            env_steps += T * B
        completed, self._completed = self._completed, []
        return {
            "batches": batches,
            "t_shape": t_shapes,
            "last_values": last_values,
            "metrics": {"env_steps": env_steps,
                        "episodes": completed},
        }


class MultiAgentPPOConfig(PPOConfig):
    """PPO over per-policy batches.  ``multi_agent(policies=...,
    policy_mapping_fn=...)`` declares the policy map (reference:
    `AlgorithmConfig.multi_agent`)."""

    def __init__(self):
        super().__init__()
        # policy_id -> (obs_dim, num_actions)
        self.policies: Dict[str, Tuple[int, int]] = {}
        self.policy_mapping_fn: Callable[[str], str] = lambda aid: aid

    def multi_agent(self, policies: Dict[str, Tuple[int, int]],
                    policy_mapping_fn: Optional[Callable] = None
                    ) -> "MultiAgentPPOConfig":
        self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO(Algorithm):
    _config_cls = MultiAgentPPOConfig

    def runner_class(self):
        return MultiAgentEnvRunner

    def runner_args(self, cfg, i: int) -> tuple:
        return (cfg.env_creator, cfg.rollout_length,
                cfg.policy_mapping_fn, cfg.seed + i)

    def build_learner(self):
        import jax
        import optax

        from ray_tpu.rllib.models import init_mlp_policy

        cfg: MultiAgentPPOConfig = self.algo_config
        assert cfg.policies, "config.multi_agent(policies=...) missing"
        self._params: Dict[str, Any] = {}
        self._opt_states: Dict[str, Any] = {}
        self._optimizer = optax.adam(cfg.lr)
        self._update = _make_update_fn(cfg, self._optimizer)
        for i, (pid, (obs_dim, n_act)) in enumerate(
                sorted(cfg.policies.items())):
            self._params[pid] = init_mlp_policy(
                jax.random.PRNGKey(cfg.seed + 101 + i), obs_dim, n_act,
                cfg.hidden)
            self._opt_states[pid] = self._optimizer.init(self._params[pid])
        self._rng = jax.random.PRNGKey(cfg.seed + 1)

    def get_weights(self):
        import jax

        return {pid: jax.tree.map(np.asarray, p)
                for pid, p in self._params.items()}

    def set_weights(self, weights):
        self._params = weights

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg: MultiAgentPPOConfig = self.algo_config
        rollouts = self.synchronous_parallel_sample()
        # assemble one train batch PER POLICY across runners
        per_policy: Dict[str, List[SampleBatch]] = {}
        for ro in rollouts:
            for pid, b in ro["batches"].items():
                T, B = ro["t_shape"][pid]
                adv, targets = compute_gae(
                    b[REWARDS].reshape(T, B), b[VALUES].reshape(T, B),
                    b[DONES].reshape(T, B), ro["last_values"][pid],
                    cfg.gamma, cfg.gae_lambda)
                b[ADVANTAGES] = adv.reshape(T * B).astype(np.float32)
                b[TARGETS] = targets.reshape(T * B).astype(np.float32)
                per_policy.setdefault(pid, []).append(b)
        metrics: Dict[str, Any] = {}
        for pid, batches in per_policy.items():
            tb = SampleBatch.concat(batches)
            learn = {
                OBS: tb[OBS], ACTIONS: tb[ACTIONS], LOGPS: tb[LOGPS],
                VALUES: tb[VALUES], ADVANTAGES: tb[ADVANTAGES],
                TARGETS: tb[TARGETS],
            }
            self._rng, sub = jax.random.split(self._rng)
            self._params[pid], self._opt_states[pid], m = self._update(
                self._params[pid], self._opt_states[pid], learn, sub)
            metrics[f"{pid}/policy_loss"] = float(m["policy_loss"])
            metrics[f"{pid}/entropy"] = float(m["entropy"])
        steps = sum(ro["metrics"]["env_steps"] for ro in rollouts)
        metrics["_steps_this_iter"] = steps
        self.sync_weights()
        return metrics
