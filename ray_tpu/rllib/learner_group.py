"""LearnerGroup — data-parallel learner fan-out over actor replicas.

Reference analogue: `rllib/core/learner/learner_group.py:61` (N Learner
workers, each holding a replica of the module, gradients averaged across
them per update).  TPU-first twist: each replica's update is the
algorithm's existing jitted program; only the GRADIENT allreduce crosses
processes, over the host collective group
(`ray_tpu/collective` — the DCN plane; on real multi-host TPU the same
update runs under pjit with psum instead).

The factory seam keeps this algorithm-agnostic: the driver ships a
cloudpickled ``factory()`` returning

    {"params", "opt_state", "grad_fn": (params, batch) -> (grads, metrics),
     "apply_fn": (params, opt_state, grads) -> (params, opt_state)}

Each replica computes grads on its shard, allreduce-means them, and
applies the identical averaged update — replicas stay in lockstep, so
weights can be read from any one of them.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["LearnerGroup", "LearnerWorker"]


class LearnerWorker:
    """One learner replica (runs as an actor)."""

    def __init__(self, factory_blob: bytes, world: int, rank: int,
                 group_name: str):
        import cloudpickle

        from ray_tpu import collective as col

        built = cloudpickle.loads(factory_blob)()
        self._params = built["params"]
        self._opt_state = built["opt_state"]
        self._grad_fn = built["grad_fn"]
        self._apply_fn = built["apply_fn"]
        self._world = world
        self._group = group_name
        if world > 1:
            col.init_collective_group(world, rank, backend="host",
                                      group_name=group_name)

    def update(self, shard: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        from ray_tpu import collective as col

        grads, metrics = self._grad_fn(self._params, shard)
        if self._world > 1:
            # ONE allreduce of the concatenated flat gradient (leaf-per-call
            # would pay the host-group round trip per tensor)
            leaves, treedef = jax.tree.flatten(grads)
            sizes = [int(np.prod(l.shape)) for l in leaves]
            flat = np.concatenate(
                [np.asarray(l, np.float32).ravel() for l in leaves])
            flat = col.allreduce(flat, group_name=self._group) / self._world
            out, off = [], 0
            for leaf, n in zip(leaves, sizes):
                out.append(jnp.asarray(
                    flat[off:off + n].reshape(leaf.shape)))
                off += n
            grads = jax.tree.unflatten(treedef, out)
        self._params, self._opt_state = self._apply_fn(
            self._params, self._opt_state, grads)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)

    def set_weights(self, weights):
        self._params = weights
        return True


class LearnerGroup:
    """Driver-side handle: shards each batch across the replicas, runs
    their updates in lockstep, and reads weights from replica 0."""

    _seq = 0

    def __init__(self, factory, num_learners: int,
                 resources: Optional[Dict[str, float]] = None):
        import cloudpickle

        import ray_tpu

        LearnerGroup._seq += 1
        group_name = f"learner_group_{LearnerGroup._seq}"
        blob = cloudpickle.dumps(factory)
        res = resources or {}
        worker_cls = ray_tpu.remote(
            num_cpus=res.get("CPU", 1), max_restarts=0,
            runtime_env={"env_vars": {"JAX_PLATFORMS": "cpu"}},
        )(LearnerWorker)
        self._workers = [
            worker_cls.remote(blob, num_learners, rank, group_name)
            for rank in range(num_learners)
        ]
        self.num_learners = num_learners

    @staticmethod
    def _shard(batch: Dict[str, np.ndarray], n: int, axis_map=None
               ) -> List[Dict[str, np.ndarray]]:
        """Split every array along its batch axis (default 0; axis_map
        overrides per key — IMPALA's time-major arrays split on axis 1)."""
        shards = [dict() for _ in range(n)]
        for k, v in batch.items():
            v = np.asarray(v)
            ax = (axis_map or {}).get(k, 0)
            if v.shape[ax] < n:
                # an empty shard's mean-based loss is NaN, and the
                # allreduce would poison every replica — fail loudly
                raise ValueError(
                    f"batch axis {ax} of {k!r} ({v.shape[ax]}) is smaller "
                    f"than num_learners ({n}); use fewer learners or "
                    f"bigger batches")
            parts = np.array_split(v, n, axis=ax)
            for i in range(n):
                shards[i][k] = parts[i]
        return shards

    def update(self, batch: Dict[str, np.ndarray], axis_map=None
               ) -> Dict[str, float]:
        import ray_tpu

        shards = self._shard(batch, self.num_learners, axis_map)
        metrics = ray_tpu.get(
            [w.update.remote(s) for w, s in zip(self._workers, shards)],
            timeout=300)
        return metrics[0]

    def get_weights(self):
        import ray_tpu

        return ray_tpu.get(self._workers[0].get_weights.remote(),
                           timeout=120)

    def get_all_weights(self) -> List[Any]:
        """Every replica's weights (tests assert lockstep)."""
        import ray_tpu

        return ray_tpu.get(
            [w.get_weights.remote() for w in self._workers], timeout=120)

    def set_weights(self, weights):
        """Checkpoint restore: push identical weights into every replica."""
        import ray_tpu

        ray_tpu.get([w.set_weights.remote(weights) for w in self._workers],
                    timeout=120)

    def shutdown(self):
        import ray_tpu

        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
