"""PPO — clipped-surrogate policy optimization with GAE.

Reference analogues: `rllib/algorithms/ppo/ppo.py:420` (``training_step``:
sample -> train -> sync weights), `rllib/core/learner/learner.py:229`
(gradient computation/update), `rllib/evaluation/postprocessing.py`
(``compute_gae_for_sample_batch``).

TPU-first: the whole update (losses, grads, adamw, minibatch epochs) jits
to one XLA program via ``lax.scan`` over shuffled minibatches — the
learner runs on whatever device jax puts it on (TPU for Atari-scale,
CPU in tests); env stepping stays on CPU runner actors.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    TARGETS,
    VALUES,
    SampleBatch,
)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.gae_lambda = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 512
        self.grad_clip = 0.5
        self.hidden = (64, 64)

    def build(self) -> "PPO":
        return PPO(self)


def compute_gae(rewards, values, dones, last_values, gamma, lam):
    """Time-major (T, B) numpy GAE (reference:
    `rllib/evaluation/postprocessing.py` ``compute_advantages``)."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    gae = np.zeros_like(last_values)
    next_value = last_values
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        gae = delta + gamma * lam * nonterminal * gae
        adv[t] = gae
        next_value = values[t]
    targets = adv + values
    return adv, targets


def _make_update_fn(cfg: PPOConfig, optimizer):
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import mlp_forward

    def loss_fn(params, mb):
        logits, value = mlp_forward(params, mb[OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, mb[ACTIONS][:, None], axis=-1)[:, 0]
        ratio = jnp.exp(logp - mb[LOGPS])
        adv = mb[ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv)
        policy_loss = -surr.mean()
        # clipped value loss (reference PPO `vf_clip_param`)
        vf_err = jnp.square(value - mb[TARGETS])
        vf_clipped = mb[VALUES] + jnp.clip(
            value - mb[VALUES], -cfg.vf_clip_param, cfg.vf_clip_param)
        vf_err2 = jnp.square(vf_clipped - mb[TARGETS])
        vf_loss = 0.5 * jnp.maximum(vf_err, vf_err2).mean()
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
        total = (policy_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        kl = (mb[LOGPS] - logp).mean()
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": entropy, "kl": kl}

    def minibatch_step(carry, mb):
        params, opt_state = carry
        (_, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, mb)
        if cfg.grad_clip:
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                 for g in jax.tree.leaves(grads)))
            scale = jnp.minimum(1.0, cfg.grad_clip / (gnorm + 1e-8))
            grads = jax.tree.map(lambda g: g * scale, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return (params, opt_state), metrics

    @jax.jit
    def update(params, opt_state, batch, rng):
        """num_epochs x shuffled-minibatch SGD as ONE compiled program:
        lax.scan over a (epochs*num_mb, mb_size) gather of the batch."""
        n = batch[OBS].shape[0]
        num_mb = max(n // cfg.minibatch_size, 1)
        mb_size = n // num_mb

        def epoch_perm(key):
            return jax.random.permutation(key, n)[:num_mb * mb_size]

        keys = jax.random.split(rng, cfg.num_epochs)
        idx = jnp.concatenate([epoch_perm(k) for k in keys])
        idx = idx.reshape(cfg.num_epochs * num_mb, mb_size)
        mbs = {k: v[idx] for k, v in batch.items()}  # (steps, mb, ...)
        (params, opt_state), metrics = jax.lax.scan(
            minibatch_step, (params, opt_state), mbs)
        return params, opt_state, jax.tree.map(lambda m: m[-1], metrics)

    return update


class PPO(Algorithm):
    _config_cls = PPOConfig

    def build_learner(self):
        import jax
        import optax

        from ray_tpu.rllib.models import init_mlp_policy

        cfg: PPOConfig = self.algo_config
        probe_env = cfg.env_creator()
        obs_dim = int(np.prod(probe_env.observation_space.shape))
        num_actions = int(probe_env.action_space.n)
        probe_env.close()
        self._params = init_mlp_policy(
            jax.random.PRNGKey(cfg.seed), obs_dim, num_actions, cfg.hidden)
        self._optimizer = optax.adam(cfg.lr)
        self._opt_state = self._optimizer.init(self._params)
        self._update = _make_update_fn(cfg, self._optimizer)
        self._rng = jax.random.PRNGKey(cfg.seed + 1)

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)

    def set_weights(self, weights):
        self._params = weights

    def training_step(self) -> Dict[str, Any]:
        """sample -> GAE -> jitted minibatch-epoch update -> broadcast
        (reference `ppo.py:420`)."""
        import jax

        cfg: PPOConfig = self.algo_config
        rollouts = self.synchronous_parallel_sample()
        batches: List[SampleBatch] = []
        for ro in rollouts:
            b = ro["batch"]
            T, B = ro["t_shape"]
            adv, targets = compute_gae(
                b[REWARDS].reshape(T, B), b[VALUES].reshape(T, B),
                b[DONES].reshape(T, B), ro["last_values"],
                cfg.gamma, cfg.gae_lambda)
            b[ADVANTAGES] = adv.reshape(T * B).astype(np.float32)
            b[TARGETS] = targets.reshape(T * B).astype(np.float32)
            batches.append(b)
        train_batch = SampleBatch.concat(batches)
        learn_batch = {
            OBS: train_batch[OBS], ACTIONS: train_batch[ACTIONS],
            LOGPS: train_batch[LOGPS], VALUES: train_batch[VALUES],
            ADVANTAGES: train_batch[ADVANTAGES],
            TARGETS: train_batch[TARGETS],
        }
        self._rng, sub = jax.random.split(self._rng)
        self._params, self._opt_state, metrics = self._update(
            self._params, self._opt_state, learn_batch, sub)
        self.sync_weights()
        out = {k: float(v) for k, v in metrics.items()}
        out["_steps_this_iter"] = train_batch.count
        return out
