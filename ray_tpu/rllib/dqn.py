"""DQN — off-policy Q-learning with replay and target network.

Reference analogue: `rllib/algorithms/dqn/dqn.py` (double DQN + PER
defaults).  TPU-first: one jitted update (double-Q target, huber TD,
importance weights) on the learner chip; epsilon-greedy rollouts on CPU
EnvRunner actors; replay stays host-side numpy
(`ray_tpu/rllib/replay_buffers.py`).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, NEXT_OBS, OBS, REWARDS,
)

__all__ = ["DQNConfig", "DQN", "dqn_action_fn"]


def dqn_action_fn(weights, obs, key):
    """Epsilon-greedy over Q-values; epsilon rides in the weights payload
    so the learner's anneal schedule reaches the runners with every
    sync_weights.  Matches the EnvRunner action_fn contract
    (-> action, logp, value; logp/value are unused placeholders here)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.models import policy_forward

    q, _ = policy_forward(weights["params"], obs)
    greedy = jnp.argmax(q, axis=-1)
    k1, k2 = jax.random.split(key)
    rand = jax.random.randint(k1, greedy.shape, 0, q.shape[-1])
    explore = jax.random.uniform(k2, greedy.shape) < weights["epsilon"]
    action = jnp.where(explore, rand, greedy)
    zeros = jnp.zeros(greedy.shape, jnp.float32)
    return action, zeros, zeros


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size = 50_000
        self.train_batch_size = 64
        self.learning_starts = 1_000
        self.num_updates_per_iter = 32
        self.target_network_update_freq = 500   # env steps
        self.double_q = True
        self.prioritized_replay = True
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_anneal_steps = 10_000
        self.hidden = (64, 64)

    def build(self) -> "DQN":
        return DQN(self)


class DQN(Algorithm):
    _config_cls = DQNConfig

    def runner_kwargs(self) -> Dict[str, Any]:
        return {"action_fn": dqn_action_fn, "store_next_obs": True}

    # ------------------------------------------------------------- learner

    def build_learner(self):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models import init_mlp_policy, policy_forward
        from ray_tpu.rllib.replay_buffers import (
            PrioritizedReplayBuffer, ReplayBuffer,
        )

        cfg = self.algo_config
        env = cfg.env_creator()
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()

        self.params = init_mlp_policy(
            jax.random.PRNGKey(cfg.seed), obs_dim, num_actions, cfg.hidden)
        # real copies: params is donated into the jitted update, so the
        # target tree must not alias its buffers
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._opt = optax.adam(cfg.lr)
        self.opt_state = self._opt.init(self.params)
        self.buffer = (
            PrioritizedReplayBuffer(cfg.buffer_size, cfg.per_alpha,
                                    cfg.per_beta, seed=cfg.seed)
            if cfg.prioritized_replay
            else ReplayBuffer(cfg.buffer_size, seed=cfg.seed))
        self._steps_since_target_sync = 0
        gamma, double_q = cfg.gamma, cfg.double_q

        def update(params, target_params, opt_state, batch):
            def loss_fn(params):
                q_all, _ = policy_forward(params, batch[OBS])
                q = jnp.take_along_axis(
                    q_all, batch[ACTIONS][:, None], axis=-1)[:, 0]
                qt_all, _ = policy_forward(target_params, batch[NEXT_OBS])
                if double_q:
                    # action chosen by the ONLINE net, valued by the target
                    qn_all, _ = policy_forward(params, batch[NEXT_OBS])
                    a_star = jnp.argmax(qn_all, axis=-1)
                else:
                    a_star = jnp.argmax(qt_all, axis=-1)
                q_next = jnp.take_along_axis(
                    qt_all, a_star[:, None], axis=-1)[:, 0]
                target = batch[REWARDS] + gamma * (1.0 - batch[DONES]) \
                    * jax.lax.stop_gradient(q_next)
                td = q - jax.lax.stop_gradient(target)
                huber = jnp.where(jnp.abs(td) <= 1.0, 0.5 * td ** 2,
                                  jnp.abs(td) - 0.5)
                loss = jnp.mean(batch["weights"] * huber)
                return loss, jnp.abs(td)

            (loss, td_abs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td_abs

        self._update = jax.jit(update, donate_argnums=(0, 2))

    # ---------------------------------------------------------------- step

    def epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._total_env_steps
                   / max(1, cfg.epsilon_anneal_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def get_weights(self):
        import jax

        return {"params": jax.tree.map(np.asarray, self.params),
                "epsilon": np.float32(self.epsilon())}

    def set_weights(self, weights):
        import jax

        self.params = weights["params"]
        self.target_params = jax.tree.map(np.array,
                                          weights.get("target_params",
                                                      weights["params"]))
        self.opt_state = self._opt.init(self.params)

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.algo_config
        rollouts = self.synchronous_parallel_sample()
        steps_this_iter = 0
        for ro in rollouts:
            b = ro["batch"]
            n = len(b[REWARDS])
            steps_this_iter += n
            self.buffer.add({
                OBS: b[OBS], ACTIONS: b[ACTIONS], REWARDS: b[REWARDS],
                NEXT_OBS: b[NEXT_OBS], DONES: b[DONES],
            })
        self._steps_since_target_sync += steps_this_iter

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                sample = self.buffer.sample(cfg.train_batch_size)
                if "weights" not in sample:
                    sample["weights"] = np.ones(
                        cfg.train_batch_size, np.float32)
                idx = sample.pop("batch_indexes")
                self.params, self.opt_state, loss, td_abs = self._update(
                    self.params, self.target_params, self.opt_state, sample)
                losses.append(float(loss))
                if hasattr(self.buffer, "update_priorities"):
                    self.buffer.update_priorities(idx, np.asarray(td_abs))
            if self._steps_since_target_sync \
                    >= cfg.target_network_update_freq:
                self.target_params = jax.tree.map(np.array, self.params)
                self._steps_since_target_sync = 0
        self.sync_weights()
        return {
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self.epsilon(),
            "buffer_size": len(self.buffer),
            "_steps_this_iter": steps_this_iter,
        }

    def save_checkpoint(self):
        import jax

        return {
            "weights": {
                "params": jax.tree.map(np.asarray, self.params),
                "target_params": jax.tree.map(np.asarray,
                                              self.target_params),
            },
            "total_env_steps": self._total_env_steps,
        }
