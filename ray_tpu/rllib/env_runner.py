"""EnvRunner — an actor that rolls out a policy in vectorized CPU envs.

Reference analogues: `rllib/evaluation/rollout_worker.py:660`
(``RolloutWorker.sample`` — the env-step hot loop),
`rllib/env/env_runner.py:9` (the EnvRunner base).

The runner owns B gymnasium envs (SyncVectorEnv) and the current policy
weights; ``sample()`` steps T*B transitions with a jitted forward and
returns a SampleBatch (numpy — travels the object plane to the learner).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    DONES,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
    VALUES,
    SampleBatch,
)


class EnvRunner:
    def __init__(self, env_creator, num_envs: int, rollout_length: int,
                 policy_init, seed: int = 0,
                 action_fn=None, store_next_obs: bool = False):
        """env_creator() -> gymnasium.Env; policy_init(rng, obs_dim,
        num_actions) -> params (only used for shape checks on the runner —
        weights always come from the learner via set_weights).

        ``action_fn(weights, obs, key) -> (action, logp, value)`` replaces
        the default categorical-policy sampler (e.g. DQN's epsilon-greedy;
        ``weights`` is whatever the learner ships via set_weights, so
        schedules like epsilon can ride along).  ``store_next_obs`` adds
        NEXT_OBS to the batch (off-policy learners need (s, a, r, s')
        transitions; on-policy GAE does not)."""
        import gymnasium as gym
        import jax

        from ray_tpu.rllib.models import sample_action

        if action_fn is not None:
            sample_action = action_fn
        self._store_next_obs = store_next_obs

        # SAME_STEP autoreset (classic semantics): a terminated env returns
        # the reset obs in the same step() call.  gymnasium >= 1.0 defaults
        # to NEXT_STEP, where the step after termination IGNORES the action
        # and yields reward 0 — recording that as a transition injects
        # garbage gradients (~1/ep_len of the batch).
        try:
            self._envs = gym.vector.SyncVectorEnv(
                [env_creator for _ in range(num_envs)],
                autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        except TypeError:
            # No autoreset_mode kwarg.  Pre-1.0 gymnasium defaults to
            # SAME_STEP so the fallback is safe there; 1.0.x defaults to
            # NEXT_STEP but lacks the kwarg (AutoresetMode landed in 1.1),
            # so silently proceeding would record post-termination garbage.
            major, minor = (int(x) for x in gym.__version__.split(".")[:2])
            if (major, minor) >= (1, 0):
                raise RuntimeError(
                    f"gymnasium {gym.__version__} defaults to NEXT_STEP "
                    "autoreset but does not support requesting SAME_STEP "
                    "(added in 1.1) — upgrade gymnasium to >= 1.1"
                ) from None
            self._envs = gym.vector.SyncVectorEnv(
                [env_creator for _ in range(num_envs)])
        self._num_envs = num_envs
        self._T = rollout_length
        self._params = None
        self._key = jax.random.PRNGKey(seed)
        self._sample_action = jax.jit(sample_action)
        obs, _ = self._envs.reset(seed=seed)
        # keep the env's native dtype: uint8 pixels stay uint8 (the CNN
        # normalizes /255 itself); float envs stay float32
        self._obs = np.asarray(obs)
        if self._obs.dtype != np.uint8:
            self._obs = self._obs.astype(np.float32)
        # per-env running episode returns (for episode_reward metrics)
        self._ep_return = np.zeros(num_envs, np.float64)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._completed: list = []

    def set_weights(self, params):
        self._params = params
        return True

    def sample(self) -> Dict[str, Any]:
        """Roll out T steps in all envs; returns {'batch': SampleBatch,
        'metrics': {...}} — the batch carries VALUES and NEXT_OBS so the
        learner can bootstrap GAE."""
        import jax

        assert self._params is not None, "set_weights before sample"
        T, B = self._T, self._num_envs
        obs_buf = np.empty((T, B) + self._obs.shape[1:], self._obs.dtype)
        act_buf = None  # allocated from the first action (shape/dtype vary:
        # int64 (B,) for discrete policies, float32 (B, act_dim) for
        # continuous ones like SAC's tanh-Gaussian)
        logp_buf = np.empty((T, B), np.float32)
        val_buf = np.empty((T, B), np.float32)
        rew_buf = np.empty((T, B), np.float32)
        done_buf = np.empty((T, B), np.float32)
        next_obs_buf = (np.empty_like(obs_buf)
                        if self._store_next_obs else None)

        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            action, logp, value = self._sample_action(
                self._params, self._obs, sub)
            action = np.asarray(action)
            next_obs, reward, terminated, truncated, _ = self._envs.step(
                action)
            obs_buf[t] = self._obs
            if act_buf is None:
                act_buf = np.empty((T,) + action.shape, action.dtype)
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            rew_buf[t] = reward
            # GAE cuts only at TERMINATION; truncation (time limit) still
            # bootstraps — but SyncVectorEnv auto-resets, so the stored
            # next_obs after either is the reset obs and we conservatively
            # cut on both (standard for CartPole-scale tasks).
            done = np.logical_or(terminated, truncated)
            done_buf[t] = done.astype(np.float32)
            self._ep_return += reward
            self._ep_len += 1
            for i in np.nonzero(done)[0]:
                self._completed.append(
                    (float(self._ep_return[i]), int(self._ep_len[i])))
                self._ep_return[i] = 0.0
                self._ep_len[i] = 0
            if next_obs_buf is not None:
                # SAME_STEP autoreset returns the reset obs after a done;
                # that's fine for the Q target — done=1 masks the bootstrap.
                next_obs_buf[t] = np.asarray(next_obs).astype(
                    next_obs_buf.dtype)
            self._obs = np.asarray(next_obs)
            if self._obs.dtype != np.uint8:
                self._obs = self._obs.astype(np.float32)

        # bootstrap value for the final observation of each env
        self._key, sub = jax.random.split(self._key)
        _, _, last_value = self._sample_action(self._params, self._obs, sub)

        batch = SampleBatch({
            # keep the native obs shape (CNN policies need (H, W, C));
            # MLP forward flattens for itself
            OBS: obs_buf.reshape((T * B,) + obs_buf.shape[2:]),
            ACTIONS: act_buf.reshape((T * B,) + act_buf.shape[2:]),
            LOGPS: logp_buf.reshape(T * B),
            VALUES: val_buf.reshape(T * B),
            REWARDS: rew_buf.reshape(T * B),
            DONES: done_buf.reshape(T * B),
        })
        if next_obs_buf is not None:
            batch[NEXT_OBS] = next_obs_buf.reshape(
                (T * B,) + next_obs_buf.shape[2:])
        completed, self._completed = self._completed, []
        return {
            "batch": batch,
            # time-major shape + bootstrap values for learner-side GAE
            "t_shape": (T, B),
            "last_values": np.asarray(last_value, np.float32),
            "metrics": {
                "episodes": completed,
                "env_steps": T * B,
            },
        }
