"""BC — behavior cloning: offline RL from a dataset of expert transitions.

Reference analogue: `rllib/algorithms/bc/bc.py` (+ the offline data path
`rllib/offline/`).  TPU-first: the dataset is a ``ray_tpu.data.Dataset``
(or columnar dict) of OBS/ACTIONS; training is jitted supervised
cross-entropy on the learner chip; the EnvRunner actors only EVALUATE the
cloned policy (no environment interaction is used for learning —
offline).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sample_batch import ACTIONS, OBS

__all__ = ["BCConfig", "BC"]


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_updates_per_iter = 64
        self.hidden = (64, 64)
        self.dataset = None  # ray_tpu.data.Dataset | {"obs":..., "actions":...}

    def offline_data(self, dataset) -> "BCConfig":
        self.dataset = dataset
        return self

    def build(self) -> "BC":
        return BC(self)


class BC(Algorithm):
    _config_cls = BCConfig

    def build_learner(self):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models import init_mlp_policy, policy_forward

        cfg = self.algo_config
        assert cfg.dataset is not None, "config.offline_data(...) missing"
        if hasattr(cfg.dataset, "take_all"):  # ray_tpu.data.Dataset
            rows = cfg.dataset.take_all()
            obs = np.stack([r[OBS] for r in rows]).astype(np.float32)
            acts = np.asarray([r[ACTIONS] for r in rows], np.int64)
        else:
            obs = np.asarray(cfg.dataset[OBS], np.float32)
            acts = np.asarray(cfg.dataset[ACTIONS], np.int64)
        self._obs, self._acts = obs, acts

        env = cfg.env_creator()
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()
        self.params = init_mlp_policy(
            jax.random.PRNGKey(cfg.seed), obs_dim, num_actions, cfg.hidden)
        self._opt = optax.adam(cfg.lr)
        self.opt_state = self._opt.init(self.params)
        self._rng = np.random.default_rng(cfg.seed)

        def update(params, opt_state, obs_b, act_b):
            def loss_fn(params):
                logits, _ = policy_forward(params, obs_b)
                logp = jax.nn.log_softmax(logits)
                nll = -jnp.take_along_axis(
                    logp, act_b[:, None], axis=-1)[:, 0]
                return jnp.mean(nll)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update, donate_argnums=(0, 1))

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = weights
        self.opt_state = self._opt.init(self.params)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        losses = []
        n = len(self._obs)
        for _ in range(cfg.num_updates_per_iter):
            idx = self._rng.integers(0, n, size=cfg.train_batch_size)
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, self._obs[idx],
                self._acts[idx])
            losses.append(float(loss))
        # evaluation rollouts with the cloned policy (offline learning,
        # online EVALUATION — like the reference's evaluation workers)
        self.sync_weights()
        self.synchronous_parallel_sample()
        return {"loss": float(np.mean(losses)),
                "_steps_this_iter": 0}
