"""SampleBatch — columnar rollout data.

Reference analogue: `rllib/policy/sample_batch.py:98` (``SampleBatch``,
a dict of parallel arrays with concat/shuffle/minibatch helpers).
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "next_obs"
LOGPS = "logps"
VALUES = "values"
ADVANTAGES = "advantages"
TARGETS = "value_targets"


class SampleBatch(dict):
    """dict[str, np.ndarray] with equal leading dims."""

    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @staticmethod
    def concat(batches: List["SampleBatch"]) -> "SampleBatch":
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([b[k] for b in batches]) for k in keys
        })

    def shuffled_minibatches(self, minibatch_size: int,
                             rng: np.random.Generator
                             ) -> Iterator["SampleBatch"]:
        n = self.count
        perm = rng.permutation(n)
        for start in range(0, n - minibatch_size + 1, minibatch_size):
            idx = perm[start:start + minibatch_size]
            yield SampleBatch({k: v[idx] for k, v in self.items()})
