"""ray_tpu.rllib — RL training: EnvRunner actors + jitted learners.

Reference analogue: the `rllib/` tree (Algorithm/RolloutWorker/
SampleBatch/Learner).  Scope here is the new-stack core: ``Algorithm``
(a Tune Trainable driving EnvRunner actors, `algorithm.py`), ``PPO``
(`ppo.py` — GAE + clipped surrogate, the whole update one jitted XLA
program), ``SampleBatch`` (`sample_batch.py`), pure-JAX policy models
(`models.py`).

Usage:
    config = (PPOConfig()
              .environment(lambda: gymnasium.make("CartPole-v1"))
              .env_runners(num_env_runners=4))
    algo = config.build()
    while algo.train()["episode_reward_mean"] < 450: pass

``PPO`` is a ``tune.Trainable`` — pass it (or a config dict) straight to
``tune.Tuner`` for PBT-over-PPO (the reference's flagship Tune+RLlib
combo).
"""

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.bc import BC, BCConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.impala import Impala, ImpalaConfig, make_vtrace_fn
from ray_tpu.rllib.learner_group import LearnerGroup, LearnerWorker
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.sac import SAC, SACConfig, sac_action_fn
from ray_tpu.rllib.replay_buffers import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.models import (
    cnn_forward,
    init_cnn_policy,
    init_mlp_policy,
    mlp_forward,
    policy_forward,
    sample_action,
)
from ray_tpu.rllib.ppo import PPO, PPOConfig, compute_gae
from ray_tpu.rllib.sample_batch import SampleBatch

__all__ = [
    "Algorithm", "AlgorithmConfig", "BC", "BCConfig", "DQN", "DQNConfig",
    "EnvRunner",
    "Impala", "ImpalaConfig", "LearnerGroup", "LearnerWorker",
    "MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentPPO",
    "MultiAgentPPOConfig", "PPO", "PPOConfig",
    "PrioritizedReplayBuffer", "ReplayBuffer", "SAC", "SACConfig",
    "SampleBatch",
    "compute_gae", "cnn_forward", "init_cnn_policy", "init_mlp_policy",
    "make_vtrace_fn", "mlp_forward", "policy_forward", "sac_action_fn",
    "sample_action",
]
