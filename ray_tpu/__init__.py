"""ray_tpu — a TPU-native distributed compute framework.

Public API surface mirrors the reference (`python/ray/__init__.py` /
`python/ray/_private/worker.py`): ``init``, ``@remote``, ``get``, ``put``,
``wait``, actors, placement groups — plus the TPU-first ML stack in
``ray_tpu.train`` / ``tune`` / ``data`` / ``serve`` / ``rllib`` and the
tensor plane in ``ray_tpu.collective`` / ``parallel`` / ``ops``.
"""

from __future__ import annotations

import inspect
from typing import Any, List, Optional, Sequence, Union

from ray_tpu._version import __version__
from ray_tpu.core import worker as _worker_mod
from ray_tpu.core.actor import ActorClass, ActorHandle, get_actor, kill
from ray_tpu.core.config import config
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.worker import (
    DriverWorker,
    LocalWorker,
    clear_worker,
    global_worker,
    init_worker,
    is_initialized,
)

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "ObjectRef", "ActorHandle",
    "placement_group", "remove_placement_group", "PlacementGroup",
    "cluster_resources", "available_resources", "nodes", "timeline",
    "RayTpuError", "TaskError", "ActorDiedError", "WorkerCrashedError",
    "GetTimeoutError", "ObjectLostError", "__version__",
]


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[dict] = None,
    object_store_memory: Optional[int] = None,
    local_mode: bool = False,
    namespace: str = "",
    ignore_reinit_error: bool = False,
    log_to_driver: bool = True,
    runtime_env: Optional[dict] = None,
    configure_logging: bool = True,
    **kwargs,
):
    """Start the runtime (reference: `python/ray/_private/worker.py:1106`)."""
    if is_initialized():
        if ignore_reinit_error:
            return
        raise RuntimeError("ray_tpu.init() called twice "
                           "(pass ignore_reinit_error=True to allow)")
    if local_mode:
        init_worker(LocalWorker())
        return
    init_worker(
        DriverWorker(
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources,
            object_store_memory=object_store_memory,
            namespace=namespace,
        )
    )


def shutdown():
    if not is_initialized():
        return
    w = global_worker()
    clear_worker()
    if hasattr(w, "shutdown"):
        w.shutdown()


def remote(*args, **kwargs):
    """``@remote`` decorator for functions and classes
    (reference: `python/ray/_private/worker.py:2923`)."""

    def wrap(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, **kwargs)
        return RemoteFunction(obj, **kwargs)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return wrap(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")
    return wrap


def method(**options):
    """Per-method options decorator (e.g. num_returns) — kept for parity;
    options can also be given at the call site via ``.options()``."""

    def wrap(m):
        m.__ray_tpu_method_options__ = options
        return m

    return wrap


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    w = global_worker()
    if isinstance(refs, ObjectRef):
        return w.get([refs], timeout=timeout)[0]
    if isinstance(refs, (list, tuple)):
        if not refs:
            return []
        if not all(isinstance(r, ObjectRef) for r in refs):
            raise TypeError("get() accepts an ObjectRef or a list of ObjectRefs")
        return w.get(list(refs), timeout=timeout)
    raise TypeError(f"get() got {type(refs)}")


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return global_worker().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if len(set(refs)) != len(refs):
        # Reference semantics: duplicate refs make num_returns ambiguous.
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns <= 0 or num_returns > len(refs):
        raise ValueError(f"num_returns must be in [1, {len(refs)}]")
    return global_worker().wait(refs, num_returns=num_returns, timeout=timeout)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Best-effort cancel of a pending task (running tasks finish; force-kill
    of running normal tasks lands with multi-node)."""
    w = global_worker()
    if w.mode != "driver":
        raise NotImplementedError("cancel() from inside tasks")

    def _cancel():
        raylet = w.raylet
        tid = ref.id().task_id()
        entry = raylet._waiting.pop(tid, None)
        found = entry is not None
        if entry is not None:
            spec, missing = entry
            for oid in missing:
                s = raylet._dep_index.get(oid)
                if s:
                    s.discard(tid)
        for q in (raylet._ready_queue,):
            for spec in list(q):
                if spec.task_id == tid:
                    q.remove(spec)
                    found = True
        if found:
            from ray_tpu.core.exceptions import TaskError as _TE

            err = _TE("cancelled", "task was cancelled before it ran", None)
            raylet._object_error(ref.id(), err)
        return found

    w.raylet.call(_cancel).result()


def free(refs: Sequence[ObjectRef]):
    global_worker().free(list(refs))


def cluster_resources() -> dict:
    w = global_worker()
    if w.mode == "driver":
        return dict(w.raylet.resources_total)
    return {}


def available_resources() -> dict:
    w = global_worker()
    if w.mode == "driver":
        return w.raylet.call(lambda: dict(w.raylet.resources_available)).result()
    return {}


def nodes() -> List[dict]:
    w = global_worker()
    if w.mode == "driver":
        snap = w.raylet.call(w.raylet.state_snapshot).result()
        return [{
            "NodeID": snap["node_id"],
            "Alive": True,
            "Resources": snap["resources_total"],
        }]
    return []


def timeline(filename: Optional[str] = None):
    """Dump task state events as chrome://tracing JSON
    (reference: `python/ray/_private/state.py:416`)."""
    import json

    w = global_worker()
    snap = w.raylet.call(w.raylet.state_snapshot).result()
    events = []
    starts = {}
    for ev in snap["events"]:
        if ev["state"] == "RUNNING":
            starts[ev["task_id"]] = ev
        elif ev["state"] in ("FINISHED", "FAILED") and ev["task_id"] in starts:
            s = starts.pop(ev["task_id"])
            events.append({
                "cat": "task", "name": s["name"], "ph": "X",
                "ts": s["time"] * 1e6, "dur": (ev["time"] - s["time"]) * 1e6,
                "pid": s.get("pid", 0), "tid": s.get("pid", 0),
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


# Convenience namespaced access (lazy imports to keep `import ray_tpu` light).
def __getattr__(name):
    if name in ("train", "tune", "data", "serve", "rllib", "collective",
                "parallel", "ops", "models", "util"):
        import importlib

        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
