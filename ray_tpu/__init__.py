"""ray_tpu — a TPU-native distributed compute framework.

Public API surface mirrors the reference (`python/ray/__init__.py` /
`python/ray/_private/worker.py`): ``init``, ``@remote``, ``get``, ``put``,
``wait``, actors, placement groups — plus the TPU-first ML stack in
``ray_tpu.train`` / ``tune`` / ``data`` / ``serve`` / ``rllib`` and the
tensor plane in ``ray_tpu.collective`` / ``parallel`` / ``ops``.
"""

from __future__ import annotations

import inspect
from typing import Any, List, Optional, Sequence, Union

from ray_tpu._version import __version__
from ray_tpu.core import worker as _worker_mod
from ray_tpu.core.actor import ActorClass, ActorHandle, get_actor, kill
from ray_tpu.core.config import config
from ray_tpu.core.exceptions import (
    ActorDiedError,
    BackPressureError,
    DeadlineExceededError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.worker import (
    DriverWorker,
    LocalWorker,
    clear_worker,
    global_worker,
    init_worker,
    is_initialized,
)

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "ObjectRef", "ActorHandle",
    "placement_group", "remove_placement_group", "PlacementGroup",
    "cluster_resources", "available_resources", "nodes", "timeline",
    "RayTpuError", "TaskError", "ActorDiedError", "WorkerCrashedError",
    "GetTimeoutError", "ObjectLostError", "DeadlineExceededError",
    "TaskCancelledError", "BackPressureError", "OutOfMemoryError",
    "__version__",
]


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[dict] = None,
    object_store_memory: Optional[int] = None,
    local_mode: bool = False,
    namespace: str = "",
    ignore_reinit_error: bool = False,
    log_to_driver: bool = True,
    runtime_env: Optional[dict] = None,
    configure_logging: bool = True,
    **kwargs,
):
    """Start the runtime (reference: `python/ray/_private/worker.py:1106`).

    ``address``: GCS address ``"host:port"`` to join an existing cluster as
    a driver (reference ``ray.init(address=...)``); None starts the embedded
    single-node runtime.
    """
    if is_initialized():
        if ignore_reinit_error:
            return
        raise RuntimeError("ray_tpu.init() called twice "
                           "(pass ignore_reinit_error=True to allow)")
    if local_mode:
        init_worker(LocalWorker())
        return
    if address is None:
        # Auto-attach for entrypoints launched by the job manager
        # (reference: RAY_ADDRESS handling in ray.init).
        address = config.address or None
    if address is not None:
        from ray_tpu.core.client import ClientWorker

        # "ray://host:port" (reference Ray Client URI scheme,
        # `python/ray/client_builder.py:90`) and bare "host:port" both
        # attach this process as a remote driver — the client-mode
        # ClientWorker IS the remote-driver proxy here (same TCP path for
        # local and remote drivers; no separate proxy server needed).
        if address.startswith("ray://"):
            address = address[len("ray://"):]
        init_worker(ClientWorker(address, log_to_driver=log_to_driver))
        return
    init_worker(
        DriverWorker(
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources,
            object_store_memory=object_store_memory,
            namespace=namespace,
        )
    )


def shutdown():
    if not is_initialized():
        return
    # Final synchronous metrics flush BEFORE the worker goes away (the
    # daemon flusher would drop the last window) + flusher/producer reset
    # so a re-init in this process doesn't double-report.
    try:
        from ray_tpu.util.metrics import shutdown_metrics

        shutdown_metrics()
    except Exception:  # noqa: BLE001
        pass
    w = global_worker()
    clear_worker()
    if hasattr(w, "shutdown"):
        w.shutdown()


def remote(*args, **kwargs):
    """``@remote`` decorator for functions and classes
    (reference: `python/ray/_private/worker.py:2923`)."""

    def wrap(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, **kwargs)
        return RemoteFunction(obj, **kwargs)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return wrap(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")
    return wrap


def method(**options):
    """Per-method options decorator (e.g. num_returns) — kept for parity;
    options can also be given at the call site via ``.options()``."""

    def wrap(m):
        m.__ray_tpu_method_options__ = options
        return m

    return wrap


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    w = global_worker()
    if isinstance(refs, ObjectRef):
        return w.get([refs], timeout=timeout)[0]
    if isinstance(refs, (list, tuple)):
        if not refs:
            return []
        if not all(isinstance(r, ObjectRef) for r in refs):
            raise TypeError("get() accepts an ObjectRef or a list of ObjectRefs")
        return w.get(list(refs), timeout=timeout)
    raise TypeError(f"get() got {type(refs)}")


def put(value: Any, *, _replicate: bool = False) -> ObjectRef:
    """``_replicate=True`` eagerly pushes a secondary copy of the object
    to another node (cheap availability: losing the holder then costs a
    pull from the replica, not a lineage recompute)."""
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return global_worker().put(value, _replicate=_replicate)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if len(set(refs)) != len(refs):
        # Reference semantics: duplicate refs make num_returns ambiguous.
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns <= 0 or num_returns > len(refs):
        raise ValueError(f"num_returns must be in [1, {len(refs)}]")
    return global_worker().wait(refs, num_returns=num_returns, timeout=timeout)


def get_runtime_context():
    """Where am I running? (reference: ``ray.get_runtime_context``,
    `python/ray/runtime_context.py`)."""
    from ray_tpu.runtime_context import get_runtime_context as _grc

    return _grc()


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Cancel a task (reference: ``ray.cancel``): queued work is dropped
    (``TaskCancelledError`` on its returns), RUNNING work is interrupted
    at the next bytecode boundary, and with ``recursive=True`` (default)
    the cancel fans out to every task the target spawned — a timed-out
    request does not orphan its downstream tree.  Cancel frames reach
    directly-dialed callees (PR 11 transport) as well as raylet queues.
    Returns True if anything was found to cancel."""
    w = global_worker()
    return w.cancel(ref, force=force, recursive=recursive)


def free(refs: Sequence[ObjectRef]):
    global_worker().free(list(refs))


def cluster_resources() -> dict:
    """Aggregate TOTAL resources across alive nodes."""
    w = global_worker()
    if w.mode == "local":
        return {}
    total: dict = {}
    for n in w.gcs_nodes():
        if n.get("alive"):
            for k, v in n["resources_total"].items():
                total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> dict:
    w = global_worker()
    if w.mode == "driver":
        return w.raylet.call(lambda: dict(w.raylet.resources_available)).result()
    if w.mode == "client":
        return w._request("available_resources")
    return {}


def nodes() -> List[dict]:
    """Cluster membership (reference: ``ray.nodes()``)."""
    w = global_worker()
    if w.mode == "local":
        return []
    return [{
        "NodeID": n["node_id"],
        "Alive": n.get("alive", True),
        "Suspect": bool(n.get("suspect")),
        "Draining": bool(n.get("draining")),
        "Incarnation": n.get("incarnation", 0),
        "Resources": n.get("resources_total", {}),
        "Address": n.get("address"),
        "Hostname": n.get("hostname", ""),
    } for n in w.gcs_nodes()]


def timeline(filename: Optional[str] = None):
    """Dump the CLUSTER-WIDE task timeline as chrome://tracing JSON
    (reference: `python/ray/_private/state.py:416`), fed by the GCS
    task-event table: per-task queue-wait vs run sub-slices, open-ended
    slices for still-running tasks (they are not silently dropped), and —
    when tracing is enabled — flow arrows from the driver's submit spans
    to the matching run slices."""
    import json

    from ray_tpu.util import tracing as _tracing
    from ray_tpu.util.state import build_timeline, raw_task_events

    w = global_worker()
    events = [] if w.mode == "local" else raw_task_events()
    spans = None
    if _tracing.tracing_enabled():
        # flow-arrow feed: per-process JSONL files when a trace dir is
        # configured, else the cluster-wide GCS trace table
        spans = _tracing.read_spans(name_prefix="task.submit")
        if not spans and w.mode != "local":
            from ray_tpu.util.state import list_trace_spans

            spans = [sp for sp in list_trace_spans()
                     if str(sp.get("name", "")).startswith("task.submit")]
    trace = build_timeline(events, spans=spans)
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


# Convenience namespaced access (lazy imports to keep `import ray_tpu` light).
def __getattr__(name):
    if name in ("train", "tune", "data", "serve", "rllib", "collective",
                "parallel", "ops", "models", "util"):
        import importlib

        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
