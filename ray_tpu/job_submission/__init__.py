"""Job submission: run driver scripts ON the cluster, track them, tail logs.

Reference analogue: `dashboard/modules/job/job_manager.py:516` (JobManager),
`:140` (JobSupervisor actor), SDK `python/ray/job_submission/`.  Same shape
here: ``submit_job`` starts a named JobSupervisor actor that execs the
entrypoint as a subprocess with ``RAY_TPU_ADDRESS`` exported (so the
entrypoint's ``ray_tpu.init()`` auto-attaches to this cluster); status and
logs persist in the GCS KV so they outlive both the client and the
supervisor.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

__all__ = ["JobStatus", "JobSubmissionClient", "JobInfo"]

_NS = "jobs"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class JobInfo(dict):
    """Dict with attribute access: status, entrypoint, submission_id,
    start_time, end_time, metadata, message."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k) from None


class _JobSupervisor:
    """Actor running ONE job entrypoint as a child process (reference:
    `job_manager.py:140`).  Runs on the cluster; writes status + log
    transitions to the GCS KV under ``jobs/<id>``."""

    def __init__(self, submission_id: str, entrypoint: str,
                 gcs_address: str, env_vars: Optional[Dict[str, str]],
                 metadata: Optional[Dict[str, str]]):
        import subprocess
        import threading

        from ray_tpu.core.worker import global_worker

        self._id = submission_id
        self._worker = global_worker()
        self._log_chunks: List[str] = []
        self._stopped = False
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = gcs_address
        env["RAY_TPU_JOB_ID"] = submission_id
        env.update(env_vars or {})
        self._put_info({
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": JobStatus.RUNNING,
            "start_time": time.time(),
            "end_time": None,
            "metadata": metadata or {},
            "message": "",
        })
        # Own process group so stop() can kill the whole entrypoint tree
        # (shell + grandchildren), like the reference supervisor does.
        self._proc = subprocess.Popen(
            entrypoint, shell=True, env=env, start_new_session=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        self._pump = threading.Thread(target=self._pump_logs,
                                      name="job-log-pump", daemon=True)
        self._pump.start()

    def _put_info(self, info: dict):
        self._worker.kv_put(self._id.encode(),
                            json.dumps(info).encode(), namespace=_NS)

    def _get_info(self) -> dict:
        raw = self._worker.kv_get(self._id.encode(), namespace=_NS)
        return json.loads(raw) if raw else {}

    def _pump_logs(self):
        import time as _time

        last_flush = 0.0
        for line in self._proc.stdout:
            self._log_chunks.append(line)
            # Periodic partial flush: the dashboard's logs endpoint reads
            # the KV, so live jobs are tail-able over HTTP too.
            now = _time.monotonic()
            if now - last_flush > 2.0:
                last_flush = now
                try:
                    self._worker.kv_put(
                        (self._id + "/logs").encode(),
                        "".join(self._log_chunks).encode(), namespace=_NS)
                except Exception:  # noqa: BLE001
                    pass
        rc = self._proc.wait()
        info = self._get_info()
        info["end_time"] = time.time()
        if self._stopped:
            info["status"] = JobStatus.STOPPED
            info["message"] = "stopped by user"
        elif rc == 0:
            info["status"] = JobStatus.SUCCEEDED
        else:
            info["status"] = JobStatus.FAILED
            info["message"] = f"entrypoint exited with code {rc}"
        self._put_info(info)
        # Persist full logs so they survive this actor.
        self._worker.kv_put((self._id + "/logs").encode(),
                            "".join(self._log_chunks).encode(), namespace=_NS)

    def logs(self, offset: int = 0) -> str:
        return "".join(self._log_chunks[offset:])

    def logs_since(self, offset: int):
        """Atomic (text, next_offset) — the tail cursor and the text come
        from one snapshot, so concurrent appends are never skipped."""
        chunks = self._log_chunks[offset:]
        return "".join(chunks), offset + len(chunks)

    def log_chunk_count(self) -> int:
        return len(self._log_chunks)

    def running(self) -> bool:
        return self._proc.poll() is None

    def stop(self) -> bool:
        import signal

        if self._proc.poll() is None:
            self._stopped = True
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                try:
                    os.killpg(os.getpgid(self._proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    self._proc.kill()
            return True
        return False

    def pid(self) -> int:
        return self._proc.pid


class JobSubmissionClient:
    """SDK + CLI backend (reference: `python/ray/job_submission/sdk.py`).
    Connects as a driver to the cluster at ``address``."""

    def __init__(self, address: str):
        import ray_tpu

        self._address = address
        ray_tpu.init(address=address, ignore_reinit_error=True)
        self._ray = ray_tpu

    # ------------------------------------------------------------- submit

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   num_cpus: float = 0) -> str:
        submission_id = submission_id or f"job-{uuid.uuid4().hex[:10]}"
        existing = self._kv_info(submission_id)
        if existing is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        env_vars = (runtime_env or {}).get("env_vars")
        supervisor = (
            self._ray.remote(_JobSupervisor)
            .options(name=f"_job_supervisor:{submission_id}",
                     num_cpus=num_cpus, max_restarts=0)
            .remote(submission_id, entrypoint, self._address,
                    env_vars, metadata))
        # Block until the supervisor is up and the KV record exists — after
        # this, status/logs work even if this client goes away.
        self._ray.get(supervisor.pid.remote())
        return submission_id

    # -------------------------------------------------------------- query

    def _kv_info(self, submission_id: str) -> Optional[dict]:
        from ray_tpu.core.worker import global_worker

        raw = global_worker().kv_get(submission_id.encode(), namespace=_NS)
        return json.loads(raw) if raw else None

    def _supervisor(self, submission_id: str):
        try:
            return self._ray.get_actor(f"_job_supervisor:{submission_id}")
        except ValueError:
            return None

    def get_job_info(self, submission_id: str) -> JobInfo:
        info = self._kv_info(submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        return JobInfo(info)

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def list_jobs(self) -> List[JobInfo]:
        from ray_tpu.core.worker import global_worker

        w = global_worker()
        out = []
        for key in w.kv_keys(b"", namespace=_NS):
            if key.endswith(b"/logs"):
                continue
            raw = w.kv_get(key, namespace=_NS)
            if raw:
                out.append(JobInfo(json.loads(raw)))
        return sorted(out, key=lambda j: j.get("start_time") or 0)

    def get_job_logs(self, submission_id: str) -> str:
        from ray_tpu.core.worker import global_worker

        raw = global_worker().kv_get((submission_id + "/logs").encode(),
                                     namespace=_NS)
        if raw is not None:
            return raw.decode()
        sup = self._supervisor(submission_id)
        if sup is not None:
            try:
                return self._ray.get(sup.logs.remote())
            except Exception:  # noqa: BLE001
                pass
        self.get_job_info(submission_id)  # raises if unknown job
        return ""

    def tail_job_logs(self, submission_id: str, poll_s: float = 0.2):
        """Generator of log text chunks until the job reaches a terminal
        state (reference SDK ``tail_job_logs``)."""
        offset = 0
        while True:
            sup = self._supervisor(submission_id)
            if sup is not None:
                try:
                    chunk, offset = self._ray.get(
                        sup.logs_since.remote(offset))
                    if chunk:
                        yield chunk
                except Exception:  # noqa: BLE001
                    pass
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                full = self.get_job_logs(submission_id)
                rest = "".join(full.splitlines(keepends=True)[offset:])
                if rest:
                    yield rest
                return
            time.sleep(poll_s)

    # ------------------------------------------------------------ control

    def stop_job(self, submission_id: str) -> bool:
        sup = self._supervisor(submission_id)
        if sup is None:
            return False
        return self._ray.get(sup.stop.remote())

    def delete_job(self, submission_id: str) -> bool:
        from ray_tpu.core.worker import global_worker

        info = self._kv_info(submission_id)
        if info is None:
            return False
        if info["status"] not in JobStatus.TERMINAL:
            raise RuntimeError(
                f"job {submission_id!r} is {info['status']}; stop it first")
        w = global_worker()
        w.kv_del(submission_id.encode(), namespace=_NS)
        w.kv_del((submission_id + "/logs").encode(), namespace=_NS)
        return True

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 60.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.1)
        raise TimeoutError(
            f"job {submission_id!r} still "
            f"{self.get_job_status(submission_id)} after {timeout}s")
