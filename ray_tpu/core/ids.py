"""Unique identifiers for jobs, tasks, actors, objects, nodes.

Design notes (vs reference `src/ray/common/id.h`): the reference derives
ObjectIDs from the owning TaskID plus a return-index so that ownership can be
recovered from the ID alone.  We keep that property: an ``ObjectID`` is the
16-byte TaskID of the task that created it concatenated with a 4-byte
little-endian index.  ``put`` objects use a per-worker synthetic "put task" id.

All IDs are immutable value types backed by ``bytes`` and are cheap to hash,
compare, and ship over the wire.
"""

from __future__ import annotations

import os
import random
import struct
import threading

_JOB_ID_SIZE = 4
_UNIQUE_ID_SIZE = 16
_OBJECT_INDEX_SIZE = 4

# Process-local PRNG for ID minting.  ``os.urandom`` is a syscall per call
# (~14us on sandboxed/para-virtualized hosts — it was the single largest
# line in the task-submission profile at one TaskID per .remote()); a
# Mersenne generator seeded once per process from 32 urandom bytes keeps
# the same collision odds for our purposes (IDs only need uniqueness, not
# unpredictability) at ~0.5us per ID.  Forked children reseed via the
# at-fork hook (getpid is itself a syscall on these hosts, so no per-call
# pid check).
_rng: "random.Random | None" = None
_rng_lock = threading.Lock()


def _reseed():
    global _rng, _rng_lock
    _rng = None
    # The parent may have been mid-mint at fork time, leaving the copied
    # lock held forever in the child — replace it, don't just reseed.
    _rng_lock = threading.Lock()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reseed)


def _random_bytes(n: int) -> bytes:
    global _rng
    with _rng_lock:
        if _rng is None:
            _rng = random.Random(os.urandom(32))
        return _rng.getrandbits(n * 8).to_bytes(n, "little")


class BaseID:
    __slots__ = ("_bytes", "_hash", "_hex")
    SIZE = _UNIQUE_ID_SIZE

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {binary!r}"
            )
        self._bytes = binary
        self._hash = None  # computed lazily; ids key hot-path dicts
        self._hex = None  # ditto; task events / object tables key by hex

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        h = self._hex
        if h is None:
            h = self._hex = self._bytes.hex()
        return h

    def __hash__(self):
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bytes))
        return h

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE


class NodeID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class WorkerID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class ActorID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class FunctionID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class TaskID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class ObjectID(BaseID):
    """TaskID (16 bytes) + return index (4 bytes LE)."""

    SIZE = _UNIQUE_ID_SIZE + _OBJECT_INDEX_SIZE

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack("<I", index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_UNIQUE_ID_SIZE])

    def return_index(self) -> int:
        return struct.unpack("<I", self._bytes[_UNIQUE_ID_SIZE:])[0]


# Convenient alias matching the public API name.
ObjectRefID = ObjectID


class _PutCounter:
    """Per-process counter used to mint ObjectIDs for ``put`` calls."""

    def __init__(self):
        self._lock = threading.Lock()
        self._task_id = TaskID.from_random()
        self._index = 0

    def next_object_id(self) -> ObjectID:
        with self._lock:
            self._index += 1
            if self._index >= 2**32 - 1:
                self._task_id = TaskID.from_random()
                self._index = 1
            return ObjectID.for_task_return(self._task_id, self._index)


put_counter = _PutCounter()
