"""The node manager ("raylet") — scheduler, worker pool, object directory.

Reference analogues, re-designed for a single event-loop thread living inside
the driver process rather than a separate daemon:

  * ``NodeManager``/``ClusterTaskManager``/``LocalTaskManager``
    (`src/ray/raylet/node_manager.h:119`, `scheduling/cluster_task_manager.h:42`,
    `scheduling/local_task_manager.h:58`) → ``Raylet`` event thread: ready
    queue, dependency-gated dispatch, resource accounting.
  * ``WorkerPool`` (`src/ray/raylet/worker_pool.h:156`) → profile-keyed pools
    of subprocess workers, spawned on demand and prestarted.
  * ``DependencyManager`` (`src/ray/raylet/dependency_manager.h:51`) →
    ``_dep_index``: tasks wait until every argument object is ready, so a
    dispatched task never blocks on args.
  * GCS tables (`src/ray/gcs/gcs_server/`) → in-process dicts: KV store,
    function table, named actors, node info.  (Multi-node: these move behind
    the same message schema over gRPC.)
  * ``GcsActorManager`` (`gcs_actor_manager.cc`) → ``_ActorState`` lifecycle
    with restart-on-death (max_restarts) and FIFO per-actor call queues.

All mutable state is owned by the event thread; the driver thread interacts
only through ``call()`` (a closure posted to the loop) and workers through
their sockets.
"""

from __future__ import annotations


import heapq
import itertools
import os
import queue as _queue
import random
import selectors
import socket
import subprocess
import sys
import threading
import time
import traceback
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.core import protocol, serialization
import ray_tpu.core.direct  # noqa: F401 — registers the RAY_TPU_DIRECT_* flags
from ray_tpu.core.config import config
from ray_tpu.core.exceptions import (
    ActorDiedError,
    BackPressureError,
    DeadlineExceededError,
    ObjectLostError,
    OutOfMemoryError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.gcs import GcsClient, GcsCore
from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu.core.task_spec import (
    ACTOR_CREATION_TASK,
    ACTOR_TASK,
    NORMAL_TASK,
    STREAMING_RETURNS,
    TaskSpec,
)
from ray_tpu.util import chaos as _chaos
from ray_tpu.util import metrics as _metrics_mod
from ray_tpu.util import profiling as _profiling
from ray_tpu.util import tracing as _tracing
from ray_tpu.util.locks import make_lock
from ray_tpu.util.retry import BackoffPolicy

config.define("gcs_reconnect_timeout_s", float, 0.0,
              "GCS fault tolerance: on a lost GCS connection, retry "
              "reconnecting for this long before shutting the node down "
              "(reference: raylet<->GCS reconnect in "
              "`test_gcs_fault_tolerance.py`).  0 = shut down immediately "
              "(the default; process trees reap cleanly in tests).")
config.define("gcs_reconnect_stagger_s", float, 0.75,
              "GCS mass-reconnect de-synchronizer: every raylet sees the "
              "GCS die at the same instant, so before the FIRST reconnect "
              "dial each sleeps uniform[0, this] — the thundering herd of "
              "dials + re-registrations spreads across the window instead "
              "of landing on the restarted GCS in lockstep.  Later "
              "attempts use the jittered exponential backoff policy.")
config.define("memory_monitor_interval_s", float, 0.0,
              "OOM prevention (reference: `memory_monitor.h:52`): poll "
              "host memory every interval and kill a worker above the "
              "threshold.  0 disables (tests/opt-in).")
config.define("memory_usage_threshold", float, 0.95,
              "Usage fraction above which the worker-killing policy fires "
              "(reference: RAY_memory_usage_threshold).")
config.define("memory_usage_file", str, "",
              "Test seam: read the usage fraction from this file instead "
              "of /proc/meminfo (chaos/OOM tests).")
config.define("spillback_max_hops", int, 4,
              "Max times a task may be forwarded between nodes before it "
              "must queue where it is (guards forward ping-pong).")
config.define("object_transfer_chunk_bytes", int, 4 << 20,
              "Chunk size for raylet-to-raylet object pulls (reference: "
              "chunked gRPC push/pull, object_manager.h:117).")
config.define("ref_free_grace_s", float, 2.0,
              "Delay between an object's ref count reaching zero and the "
              "actual free (covers refs in transit inside results).")
config.define("max_lineage_entries", int, 20000,
              "Max objects whose creating TaskSpec is retained for "
              "eviction recovery (reference: lineage byte caps).")
config.define("max_object_reconstructions", int, 5,
              "Per-object lineage-reconstruction budget (reference: "
              "RAY_max_object_reconstructions / task max_retries): how "
              "many times a lost object's creating task may be re-run "
              "before get() raises ObjectLostError.  Each reconstruction "
              "also draws down the spec's retries_left, so crash retries "
              "and reconstructions share one budget.")
config.define("max_reconstruction_depth", int, 8,
              "Recursion bound for reconstructing an object's missing "
              "dependencies (a lineage chain deeper than this errors "
              "instead of re-running unboundedly).")
config.define("pull_sender_threads", int, 2,
              "Bounded sender pool for the python-fallback pull path "
              "(control-plane chunk streams).  A burst of pulls queues "
              "behind these threads instead of spawning one thread per "
              "request; saturation is counted in "
              "ray_tpu_internal_pull_sender_saturated_total.")
config.define("replication_min_bytes", int, 0,
              "Eager availability (reference: secondary object copies, "
              "SURVEY §5 failure recovery): a store object sealed at or "
              "above this size on its producing node is immediately pushed "
              "to a second node over the data plane, so losing the holder "
              "costs a pull from the replica instead of a lineage "
              "recompute (and striping across both holders doubles read "
              "bandwidth).  0 disables the auto-threshold; explicitly "
              "flagged objects (put(..., _replicate=True) / the "
              "_replicate task option) and actor checkpoints replicate "
              "regardless.")
config.define("replication_factor", int, 2,
              "Total copies (primary included) eager replication creates "
              "and re-replication maintains after a holder dies.")
config.define("replication_verify_delay_s", float, 10.0,
              "Replication pushes are fire-and-forget; this long after a "
              "push round the producer re-checks the directory and "
              "re-pushes if targets never registered their copy (dead "
              "target, store-less node, abandoned pull).  Up to 2 "
              "re-push rounds per object.")
config.define("kill_checkpoint_grace_s", float, 10.0,
              "kill(actor, no_restart=False) on a checkpointable actor "
              "asks the worker for a final checkpoint + graceful exit; "
              "if the worker has not exited after this grace (wedged "
              "call, deep queue) it is SIGKILLed like a hard kill.")
config.define("locality_aware_min_bytes", int, 1 << 20,
              "Locality-aware placement (reference: locality_aware lease "
              "policy): a task whose remote arguments hold at least this "
              "many bytes on some peer — and more than are local here — "
              "is forwarded to that peer instead of pulling the data.  "
              "0 disables.")

# ---------------------------------------------------------------------------

# Inline payload for a placement group's ready() object.
_PG_READY_BLOB = serialization.dumps(True)

# sentinel: a GCS call failed transiently (vs an authoritative None)
_GCS_ERR = object()


class SimpleFuture:
    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None

    def set(self, value=None):
        self._value = value
        self._event.set()

    def set_error(self, err):
        self._error = err
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError()
        if self._error is not None:
            raise self._error
        return self._value



def _node_topology_labels() -> Dict[str, str]:
    """Scheduler-visible TPU topology labels from the environment (SURVEY
    §7 items 3-4): a TPU-VM pod-slice worker exports its slice identity
    via the TPU runtime env (or the RAY_TPU_* overrides used in tests);
    nodes sharing ``tpu_slice`` are ICI-adjacent and STRICT_PACK bundles
    prefer staying inside one slice."""
    labels: Dict[str, str] = {}
    env = os.environ
    for key, override, tpu_var in (
            ("accelerator_type", config.accelerator_type,
             "TPU_ACCELERATOR_TYPE"),
            ("tpu_slice", config.slice_id, "TPU_NAME"),
            ("tpu_topology", config.topology, "TPU_TOPOLOGY"),
            ("tpu_worker_id", config.worker_id, "TPU_WORKER_ID"),
    ):
        val = override or env.get(tpu_var)
        if val:
            labels[key] = val
    return labels


class _WorkerConn:
    def __init__(self, sock, profile):
        self.sock = sock
        self.profile = profile
        self.worker_id: Optional[WorkerID] = None
        self.pid: Optional[int] = None
        self.state = "starting"  # starting | idle | busy | actor
        self.current_task: Optional[TaskSpec] = None
        # Concurrent actors can have several calls in flight on one worker
        # (reference: concurrency groups, `concurrency_group_manager.cc`).
        self.inflight: Dict[TaskID, TaskSpec] = {}
        # rid -> cancel fn for this worker's outstanding get/wait requests;
        # invoked on explicit cancel (client-side timeout) or worker death
        # so object waiter lists don't accumulate dead callbacks.
        self.request_cancels: Dict[int, Callable] = {}
        self.actor_id: Optional[ActorID] = None
        # oid -> hold count announced by this process (auto-released on
        # process death)
        self.held: Dict[ObjectID, int] = {}
        self.send_lock = make_lock("worker_conn.send")
        self.rbuf = bytearray()  # partial-frame receive buffer
        self.sent_fns: set = set()  # function ids this worker has cached
        # Direct transport: the worker's direct-call listener address
        # (registered at startup), whether this conn ever brokered a
        # direct channel (fence notices go only to such conns), and the
        # active lease record when the worker is leased to a caller.
        self.direct_addr: Optional[dict] = None
        self.uses_direct = False
        self.lease: Optional[dict] = None
        # set by the memory monitor just before SIGKILL, so the death
        # path raises typed OutOfMemoryError instead of a generic crash
        self.oom_kill = False

    def send(self, msg):
        protocol.send_msg(self.sock, msg, self.send_lock)

    def send_many(self, msgs):
        protocol.send_msgs(self.sock, msgs, self.send_lock)


class _ObjectState:
    __slots__ = ("status", "value", "error", "size", "locations",
                 "holders", "pins", "tracked", "creating_spec",
                 "free_armed", "contains", "remote_inline",
                 "recon_attempts", "lookup_attempts",
                 "replicated", "replicas")

    def __init__(self):
        # pending | inline | store | remote | error
        # "remote": sealed in another node's store/raylet (cluster mode) —
        # satisfies dependency gating (the task can be forwarded to the
        # data) but must be pulled before LOCAL dispatch or get().
        self.status = "pending"
        self.value: Optional[bytes] = None
        self.error: Optional[Exception] = None
        self.size = 0
        self.locations: List[str] = []
        # --- reference counting (reference: reference_count.h:61) ---
        self.holders = 0        # processes holding live ObjectRefs
        self.pins = 0           # queued/submitted tasks depending on this
        self.tracked = False    # ever held => eligible for auto-free
        self.creating_spec: Optional["TaskSpec"] = None  # lineage
        self.free_armed = False
        # ObjectIDs of refs serialized INSIDE this object's bytes: each is
        # pinned while this entry lives (borrow pinning — an inner ref must
        # outlive the blob that mentions it, however long it sits unread).
        self.contains: Optional[List["ObjectID"]] = None
        # "remote" objects: the directory says the remote copy is INLINE
        # (small, lives in the holder raylet's memory, not its store) —
        # such objects pull over the control plane, not the data channel.
        self.remote_inline = False
        # Lineage-reconstruction budget spent on this object (node death /
        # eviction re-runs of creating_spec); capped by
        # config.max_object_reconstructions.
        self.recon_attempts = 0
        # Consecutive failed directory re-lookups — drives the unified
        # backoff on pull retries; reset when the object materializes.
        self.lookup_attempts = 0
        # Eager availability: True on every node that holds a MANAGED copy
        # (the producer that pushed replicas, or a replica holder) — these
        # nodes re-replicate when a holder dies.  ``replicas`` lists the
        # nodes this raylet pushed copies to (producer side only).
        self.replicated = False
        self.replicas: Optional[List[str]] = None


class _PeerConn:
    """Connection to another raylet (either dialed or accepted)."""

    __slots__ = ("sock", "node_id", "send_lock", "rbuf", "blackholed")

    def __init__(self, sock, node_id: str):
        self.sock = sock
        self.node_id = node_id
        self.send_lock = make_lock("peer_conn.send")
        self.rbuf = bytearray()  # partial-frame receive buffer
        # Chaos blackhole: a partitioned peer conn silently swallows every
        # outbound frame (the socket stays open — failure detection must
        # come from the GCS health monitor / pull watchdogs, like a real
        # network partition).
        self.blackholed = False

    def send(self, msg):
        if self.blackholed:
            return
        fault = _chaos.net_fault("peer", peer=self.node_id)
        if fault is not None:
            if fault == "blackhole":
                self.blackholed = True
            return  # drop / blackhole: the frame vanishes
        protocol.send_msg(self.sock, msg, self.send_lock)


class _ActorState:
    def __init__(self, spec: TaskSpec, name: Optional[str]):
        self.actor_id = spec.actor_id
        self.creation_spec = spec
        self.name = name
        self.state = "pending"  # pending | alive | restarting | dead
        # Cluster mode: node the actor executes on when it was spilled to a
        # peer raylet (this raylet stays the OWNER: it holds the state
        # machine and the restart budget, the exec node reports deaths).
        self.node_id: Optional[str] = None
        # Set on the EXEC side of a forwarded actor: the owner node id
        # (deaths are reported there instead of restarting locally).
        self.foreign_owner: Optional[str] = None
        self.conn: Optional[_WorkerConn] = None
        self.queue: deque = deque()  # pending method TaskSpecs (FIFO order)
        # In-flight calls — up to max_concurrency simultaneously (reference:
        # actor scheduling queues + concurrency groups).
        self.inflight: Dict[TaskID, TaskSpec] = {}
        self.max_concurrency = max(1, spec.max_concurrency)
        # Named concurrency groups: per-group admission limits so a
        # saturated group never starves another (reference: independent
        # group scheduling queues, `concurrency_group_manager.cc`).
        self.group_limits: Optional[Dict[str, int]] = \
            getattr(spec, "concurrency_groups", None)
        self.restarts_left = spec.max_restarts
        self.death_reason = ""
        # Checkpointable actors: latest snapshot object (pinned by the
        # raylet until superseded or the actor is finally dead) + its
        # monotonic sequence number (relayed checkpoints can arrive out
        # of order around a restart).
        self.checkpoint_oid: Optional[ObjectID] = None
        self.checkpoint_seq = 0
        # Sync plain actors (max_concurrency 1, no groups, non-asyncio —
        # reported by the creation-done message) execute calls one at a
        # time on the worker's main thread, so pipelining calls ahead of
        # completion keeps effective concurrency at 1 while removing a
        # socket round-trip of dead time between calls.
        self.async_actor = False
        # Direct transport: restart generation — bumped on EVERY death, so
        # a direct channel (or an in-flight direct call reconciling via
        # the raylet) brokered against an earlier incarnation of this
        # actor is fenced instead of executing on the restarted instance.
        self.generation = 0
        # Exec-side direct address of a FORWARDED actor (owner side only;
        # piggybacked on the creation xdone) — what the broker hands to
        # callers when the actor runs on a peer node.
        self.direct_info: Optional[dict] = None

    def admit_limit(self) -> int:
        if (self.max_concurrency == 1 and self.group_limits is None
                and not self.async_actor):
            return max(1, config.actor_pipeline_depth)
        return self.max_concurrency


class _PlacementGroup:
    """Local PG (or, in cluster mode, this node's FRAGMENT of one):
    bundles keyed by their GLOBAL bundle index — a fragment holds only the
    indices the GCS assigned to this node."""

    def __init__(self, pg_id, bundles, strategy: str,
                 ready_oid: Optional[ObjectID] = None,
                 fragment: bool = False):
        if isinstance(bundles, list):
            bundles = {i: b for i, b in enumerate(bundles)}
        self.pg_id = pg_id
        self.bundles: Dict[int, Dict[str, float]] = bundles
        self.available = {i: dict(b) for i, b in bundles.items()}
        self.strategy = strategy
        self.state = "pending"  # pending | created
        self.ready_oid = ready_oid
        self.fragment = fragment  # cluster PG piece; GCS owns the whole
        # bundle indices whose node resources are NOT yet acquired.
        # Whole PGs reserve atomically (all-or-nothing, no inter-PG
        # deadlock); fragments reserve per bundle (node-death repair can
        # extend a live fragment).
        self.unreserved = set(bundles.keys())

    def reserved_total(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for i, b in self.bundles.items():
            if i in self.unreserved:
                continue
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        return total

    def total(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for b in self.bundles.values():
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        return total


def _fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in need.items())


def _acquire(avail: Dict[str, float], need: Dict[str, float]):
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


def _release(avail: Dict[str, float], need: Dict[str, float]):
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) + v


# ---------------------------------------------------------------------------


class Raylet:
    def __init__(
        self,
        session_dir: str,
        resources: Dict[str, float],
        store_path: Optional[str],
        worker_env: Optional[Dict[str, str]] = None,
        gcs: Optional[GcsCore] = None,
        gcs_address: Optional[str] = None,
        node_ip: str = "127.0.0.1",
        listen_port: Optional[int] = None,
    ):
        """Single-node (default): embedded ``GcsCore``, unix socket only.

        Cluster mode (``listen_port`` not None, usually 0 = ephemeral): also
        listens on TCP for peer raylets and remote drivers, registers the
        node with the GCS (remote via ``gcs_address`` or a shared in-process
        core via ``gcs``), heartbeats resources, spills tasks to peers and
        pulls remote objects (reference: `src/ray/raylet/main.cc:109` node
        bring-up + `scheduling/cluster_task_manager.cc:44` spillback).
        """
        self.session_dir = session_dir
        self.socket_path = os.path.join(session_dir, "raylet.sock")
        self.store_path = store_path
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.worker_env = worker_env or {}
        self.node_id = WorkerID.from_random().hex()
        self.node_ip = node_ip
        self.gcs_address = gcs_address
        self.cluster_mode = listen_port is not None
        # Registration generation assigned by the GCS (monotonic per
        # node_id).  Stamped onto heartbeats, directory updates, task-event
        # batches, actor registrations, peer hellos, and data-channel
        # handshakes — the fencing token that makes a node declared dead
        # unable to mutate cluster state until it re-registers fresh
        # (reference: raylet restarts bump the node instance id).
        self.incarnation = 0

        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._listener.bind(self.socket_path)
        self._listener.listen(128)
        self._listener.setblocking(False)

        self._tcp_listener = None
        self.tcp_port = None
        if self.cluster_mode:
            self._tcp_listener = socket.create_server(
                (node_ip, listen_port), backlog=128)
            self._tcp_listener.setblocking(False)
            self.tcp_port = self._tcp_listener.getsockname()[1]

        # Control plane: remote GCS (cluster), shared core (in-process
        # multi-raylet tests), or a private embedded core (single node).
        # A standalone raylet whose GCS dies must not linger as an orphan
        # tree of workers (reference raylets exit when the GCS is
        # unreachable); ``on_fatal`` lets the hosting process (raylet_main)
        # exit its wait loop.
        self.on_fatal: Optional[Callable[[], None]] = None
        if gcs_address is not None:
            self.gcs = GcsClient(gcs_address, push_handler=self._gcs_push,
                                 on_disconnect=self._on_gcs_lost)
        else:
            self.gcs = gcs if gcs is not None else GcsCore()

        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._inbox: deque = deque()  # guard: _inbox_lock
        self._inbox_lock = make_lock("raylet.inbox")
        # Wake elision: _wake_armed=True means the loop is GUARANTEED to
        # drain the inbox without a wake byte — either a byte is already in
        # flight, or the loop is awake and will re-check the inbox before
        # blocking in select (it disarms under the lock right before a
        # blocking select).  A submission storm while the loop is busy
        # costs ZERO syscalls instead of one send per call_async.
        self._wake_armed = False  # guard: _inbox_lock

        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listener, selectors.EVENT_READ, ("accept", None))
        self._sel.register(self._wake_r, selectors.EVENT_READ, ("wake", None))
        if self._tcp_listener is not None:
            self._sel.register(self._tcp_listener, selectors.EVENT_READ,
                               ("accept", None))

        # state (event-thread owned)
        # Batched-drain context: while a frame train is being drained,
        # actor pumps and request replies are deferred/coalesced so one
        # wakeup's worth of messages costs one pump per actor and one
        # sendall per conn instead of one each per frame.
        self._drain_depth = 0
        self._pending_pumps: "dict[ActorID, _ActorState]" = {}
        self._pending_replies: "dict[int, tuple]" = {}  # id(conn) -> (conn, [msgs])
        self._workers: Dict[socket.socket, _WorkerConn] = {}
        self._idle: Dict[str, deque] = {}  # profile -> deque[_WorkerConn]
        self._spawning: Dict[str, int] = {}
        self._procs: List[subprocess.Popen] = []
        self._unregistered: List[Tuple[subprocess.Popen, str]] = []
        self._health_timer_armed = False
        self._ready_queue: deque = deque()  # TaskSpecs with deps satisfied
        self._waiting: Dict[TaskID, Tuple[TaskSpec, set]] = {}
        self._dep_index: Dict[ObjectID, set] = {}
        self._objects: Dict[ObjectID, _ObjectState] = {}
        self._object_waiters: Dict[ObjectID, List[Callable]] = {}
        self._actors: Dict[ActorID, _ActorState] = {}
        self._pgs: Dict[str, _PlacementGroup] = {}
        # Local write-through cache of the GCS function table (hot path:
        # every dispatch of a large function looks its blob up).
        self._fn_cache: Dict[bytes, bytes] = {}
        self._timers: List[Tuple[float, int, Callable]] = []
        self._timer_seq = itertools.count()
        self._task_events: deque = deque(maxlen=config.task_event_buffer_size)
        self._task_states: Dict[TaskID, dict] = {}
        # Task-event export (reference: the raylet's TaskEventBuffer flushing
        # to the GCS task-event table): a ring buffer of not-yet-flushed
        # events, batch-flushed on a timer / drain cadence via one-way GCS
        # posts.  Overflow drops the OLDEST events and counts them —
        # export backpressure must never block dispatch.
        self._task_event_buf: deque = deque()
        self._task_event_dropped = 0        # since last flush (shipped)
        self._task_event_dropped_total = 0  # lifetime (metrics)
        self._task_event_timer_armed = False
        # Hot-path flag handles: _record_event runs 3x per task; reading
        # .value off the flag object keeps runtime toggles working (tests /
        # bench flip config.task_events) without a config __getattr__ per
        # event.
        self._flag_task_events = config._flags["task_events"]
        self._flag_event_cap = config._flags["task_event_export_buffer"]
        self._flag_state_cap = config._flags["task_event_buffer_size"]
        # Trace-span export (request-flow tracing): spans from this
        # process (raylet hop spans + driver spans — they share a process
        # in single-node mode) and from workers ("spans" control frames)
        # buffer here and batch-flush to the GCS trace table on the same
        # drain/timer cadence as task events.
        _tracing.maybe_enable_from_env()
        self._trace_buf: deque = deque()
        self._trace_export_dropped = 0        # since last flush (shipped)
        self._trace_dropped_total = 0         # lifetime (metrics)
        self._trace_timer_armed = False
        if _tracing.tracing_enabled():
            # heartbeat from the start: driver-side spans (same process,
            # different thread) reach the GCS table without waiting for a
            # raylet-side emit to arm the timer
            self._arm_trace_flush()
        # Continuous-profiling export (cluster-wide profiling): folded
        # stack samples from this process's sampler thread plus worker
        # batches ("profile_samples" control frames) buffer here and
        # batch-flush to the per-node GCS profile table on a recurring
        # timer (RAY_TPU_PROFILE=0 live kill switch idles the samplers;
        # the timer then only polls an empty buffer once a second).
        _profiling.ensure_profiler(
            "raylet" if self.cluster_mode else "driver")
        self._profile_buf: deque = deque()
        self._profile_export_dropped = 0   # since last flush (shipped)
        self._profile_dropped_total = 0    # lifetime (metrics)
        # Metric time-series export: delta points from this process's
        # registry ring plus worker batches ("metric_points" control
        # frames) buffer here and batch-flush to the per-node GCS metrics
        # table on the internal-metrics cadence.
        self._metric_point_buf: deque = deque()
        self._metric_points_export_dropped = 0  # since last flush (shipped)
        self._metric_points_dropped_total = 0   # lifetime (metrics)
        # Telemetry self-audit: subsystem -> [wall seconds, approx bytes]
        # accumulated in the export flush paths, re-exported as
        # ray_tpu_internal_telemetry_flush_* series each metrics tick.
        self._m_telemetry: Dict[str, list] = {}  # unguarded-ok: event thread + flush timers; float += races at worst lose one sample's accounting
        # in-flight live stack-dump gathers: token -> {want, procs, cb, done}
        self._stack_queries: Dict[str, dict] = {}
        self._stack_token_seq = itertools.count(1)
        # worker log-file index for `ray_tpu logs` + crash forensics
        # (path -> pid survives the tail entry, which pops at death)
        self._worker_log_pids: Dict[str, Optional[int]] = {}
        self._worker_log_by_pid: Dict[int, str] = {}
        self.add_timer(config.profile_flush_interval_s,
                       self._profile_flush_tick)
        # recovery-span bookkeeping: creating task_id -> (t0, parent_ctx,
        # oid_hex) captured when a reconstruction starts, emitted when it
        # concludes
        self._recon_trace: Dict[TaskID, tuple] = {}
        # traced arg pulls: oid -> (t0, parent_ctx); span emitted when the
        # pull seals/fails (one child span per data-channel pull)
        self._pull_trace: Dict[ObjectID, tuple] = {}
        # Internal runtime metrics (ray_tpu_internal_*): plain event-thread
        # counters sampled into util.metrics primitives at flush time.
        self._im: Optional[Dict[str, object]] = None
        self._m_frames = 0       # control-plane frames handled
        self._m_trains = 0       # socket drains (frame trains)
        self._m_train_bytes = 0
        self._m_tasks_done = {"FINISHED": 0, "FAILED": 0, "SHED": 0,
                              "EXPIRED": 0, "CANCELLED": 0}
        self._m_last: Dict[str, float] = {}  # counter deltas at flush
        # ---- overload protection / deadlines ----
        self._m_shed = 0              # backpressure rejections (queue bound)
        self._m_deadline_exceeded = 0  # deadline expiries enforced here
        self._m_cancelled = 0         # tasks cancelled (fan-out included)
        # cancel fan-out edges: parent task id -> child TASK IDS
        # submitted while it ran (relayed submits + direct_running
        # notes; ids only — retaining specs would pin their arg payloads
        # for the LRU's lifetime); bounded LRU on parents — a long-lived
        # driver must not grow this forever
        self._children: "OrderedDict[TaskID, List[TaskID]]" = OrderedDict()
        # tasks a cancel/deadline fan-out already reaped (tid -> deadline
        # flag): a child whose submit frame or direct_running note arrives
        # AFTER the fan-out walked the children index is caught here at
        # admission instead of running to completion.  Bounded LRU.
        self._cancelled_tids: "OrderedDict[TaskID, bool]" = OrderedDict()
        # direct calls currently executing on a local worker (RUNNING note
        # seen, done not yet): task id -> (hosting conn, spec).  Cancel/
        # deadline frames route to the hosting worker's control socket
        # even though dispatch never came through this raylet, and the
        # OOM victim picker sees leased workers' in-flight work through it
        self._direct_running: Dict[TaskID, tuple] = {}
        if config.internal_metrics_interval_s > 0:
            self._init_internal_metrics()
        self._need_schedule = False
        self._shutdown = False
        # Streaming generator tasks (reference: streaming generator returns,
        # `_raylet.pyx:224`): task_id -> {produced, total, error, waiters}.
        self._streams: Dict[TaskID, dict] = {}
        # Streams executing here for another raylet: task_id -> origin node
        # (each yielded item is relayed so the consumer-side stream state
        # advances — covers actor-routed and node-affinity streaming tasks).
        self._foreign_streams: Dict[TaskID, str] = {}
        # auto-free grace queue (see _maybe_free): FIFO of (deadline, oid)
        # swept by a single repeating timer instead of a timer per object
        self._free_queue: deque = deque()
        self._free_sweep_armed = False
        # lineage bookkeeping (bounded; see submit_task)
        self._lineage_count = 0
        self._reconstructing: set = set()
        # cluster PGs this node originated: pg_id -> ready ObjectID
        self._cluster_pg_ready: Dict[str, Optional[ObjectID]] = {}
        # Worker log tailing (reference: LogMonitor,
        # `python/ray/_private/log_monitor.py:102`): in cluster mode worker
        # stdio goes to per-worker files; a timer tails them and pushes new
        # lines to attached drivers.
        self._worker_log_seq = itertools.count()
        self._worker_log_tails: Dict[str, dict] = {}  # path -> {pos, pid}
        self._log_timer_armed = False

        # ---- cluster state (all event-thread owned) ----
        self._peers: Dict[str, _PeerConn] = {}          # node_id -> conn
        self._cluster_nodes: Dict[str, dict] = {}       # node_id -> gcs info
        # Fenced peers: node_id -> last incarnation declared dead.  Written
        # on the event thread (node_dead events); read by event-thread
        # peer-hello checks AND data-server handshake threads (dict get is
        # GIL-atomic; entries are independent).
        self._fenced: Dict[str, int] = {}
        self._m_fenced_frames = 0  # stale peer hellos / handshakes rejected
        # ---- graceful drain (node_drain push -> drain_complete) ----
        self._draining = False
        self._drained = False           # drain finished: stop heartbeating
        self._drain_deadline = 0.0
        self._drain_stats: Dict[str, int] = {}
        self._drain_pushed: set = set()  # oids already pushed during drain
        self._drain_push_at: Dict[ObjectID, float] = {}  # last push time
        self._forwarded: Dict[TaskID, Tuple[TaskSpec, str]] = {}
        self._actor_owner_cache: Dict[ActorID, str] = {}
        self._pulls: Dict[ObjectID, dict] = {}          # oid -> pull state
        self._pull_by_rid: Dict[int, ObjectID] = {}
        self._pull_rid = itertools.count(1)
        self._store = None  # guard: _store_lock — lazy attach, see _raylet_store
        self._store_lock = make_lock("raylet.store")  # data-plane threads attach too
        # ---- zero-copy data plane (data_channel.py + pull_manager.py) ----
        self._data_server = None
        self._pull_manager = None
        if self.cluster_mode and store_path and config.data_channel:
            from ray_tpu.core.data_channel import DataServer
            from ray_tpu.core.pull_manager import PullManager

            self._data_server = DataServer(node_ip, self._raylet_store,
                                           fence_fn=self._peer_fence_ok)
            self._pull_manager = PullManager(
                self.node_id, self._raylet_store, self._peer_data_addr,
                post=self.call_async,
                on_done=self._on_pull_done, on_fail=self._on_pull_failed,
                hello_fn=lambda: (self.node_id, self.incarnation))
        # Bounded sender pool for the python-fallback pull path (was: one
        # thread spawned per pull request).
        self._pull_send_q: Optional[_queue.SimpleQueue] = None
        self._pull_sender_count = 0
        self._m_pull_sender_saturated = 0
        self._m_locality_spills = 0
        # Lineage-reconstruction accounting (node-death + eviction recovery)
        self._m_recon_attempts = 0
        self._m_recon_successes = 0
        self._m_recon_failures = 0
        # Eager replication / actor checkpointing (cheap availability)
        self._replicating: set = set()  # oids being pulled as replicas here
        self._m_repl_pushes = 0      # replica pushes initiated
        self._m_repl_bytes = 0       # bytes covered by those pushes
        self._m_repl_repairs = 0     # re-replications after a holder died
        self._m_repl_recoveries = 0  # node-death losses served by a replica
        self._m_ckpt_saves = 0       # actor checkpoints recorded
        self._m_ckpt_bytes = 0
        self._m_ckpt_restores = 0    # restarts that restored from one
        # Unified jittered-exponential backoff for transient-failure paths
        # (GCS reconnect, pull re-lookups; data-channel dials hold their
        # own instance inside the pull manager).
        self._retry_policy = BackoffPolicy()
        # ---- direct worker→worker transport (broker-side state) ----
        # In-process driver's fence callback (DriverWorker wires it);
        # worker/driver conns that brokered direct channels get fence
        # notices as control frames instead.
        self.direct_fence_cb: Optional[Callable[[dict], None]] = None
        self._leases: Dict[str, _WorkerConn] = {}  # lease_id -> worker
        self._lease_seq = itertools.count(1)
        self._m_direct_dones = 0   # direct completions bookkept here
        self._m_direct_leases = 0  # task leases granted

        if isinstance(self.gcs, GcsCore):
            # In-process core: subscribe directly; pushes hop to the loop.
            self.gcs.subscribe(self._gcs_push, node_id=self.node_id)
        else:
            self.gcs.subscribe_remote(node_id=self.node_id)
        address = (node_ip, self.tcp_port) if self.cluster_mode else None
        self.node_labels = _node_topology_labels()
        self.data_port = (self._data_server.port
                          if self._data_server is not None else None)
        self._apply_registration(self.gcs.register_node(
            self.node_id, address, self.resources_total,
            store_path=store_path, hostname=socket.gethostname(),
            labels=self.node_labels, data_port=self.data_port,
            incarnation=self.incarnation))

        self._thread = threading.Thread(target=self._run, name="raylet", daemon=True)
        self._thread.start()
        if self.cluster_mode:
            self.call_async(
                lambda: self.add_timer(config.gcs_heartbeat_interval_s,
                                       self._heartbeat))
        if config.memory_monitor_interval_s > 0:
            self.call_async(
                lambda: self.add_timer(config.memory_monitor_interval_s,
                                       self._memory_check))
        if self._im is not None:
            self.call_async(
                lambda: self.add_timer(config.internal_metrics_interval_s,
                                       self._flush_internal_metrics))
        if self._pull_manager is not None:
            self.call_async(
                lambda: self.add_timer(1.0, self._pull_tick))

    # ------------------------------------------------------------------ API
    # Called from the driver thread; closures run on the event thread.

    def call(self, fn: Callable, *args) -> SimpleFuture:
        fut = SimpleFuture()

        def wrapper():
            try:
                fut.set(fn(*args))
            except BaseException as e:  # noqa: BLE001
                fut.set_error(e)

        with self._inbox_lock:
            self._inbox.append(wrapper)
            need_wake = not self._wake_armed
            self._wake_armed = True
        if need_wake:
            try:
                self._wake_w.send(b"\x00")
            except OSError:
                pass
        return fut

    def call_async(self, fn: Callable, *args):
        with self._inbox_lock:
            self._inbox.append(lambda: fn(*args))
            need_wake = not self._wake_armed
            self._wake_armed = True
        if need_wake:
            try:
                self._wake_w.send(b"\x00")
            except OSError:
                pass

    # --------------------------------------------------------------- event loop

    def _run(self):
        while not self._shutdown:
            # The inbox is drained every iteration (not only on wake bytes:
            # elided wakes rely on this — see _wake_armed).
            self._drain_inbox()
            # Debounced scheduling: submit/done storms request a schedule
            # pass via the flag; ONE queue scan runs per loop iteration
            # instead of one per message (a 2000-task burst is otherwise an
            # O(n^2) rescan of the deferred queue).
            if self._need_schedule:
                self._need_schedule = False
                self._safe(self._schedule_now)
            timeout = 0.0 if self._need_schedule else self._next_timer_delay()
            if timeout != 0.0:
                with self._inbox_lock:
                    if self._inbox:
                        timeout = 0.0  # drained next iteration; stay armed
                    else:
                        # about to block: from here on a caller must send a
                        # wake byte to interrupt the select
                        self._wake_armed = False
            events = self._sel.select(timeout)
            now = time.monotonic()
            while self._timers and self._timers[0][0] <= now:
                _, _, cb = heapq.heappop(self._timers)
                self._safe(cb)
            for key, _ in events:
                kind, conn = key.data
                if kind == "accept":
                    self._accept(key.fileobj)
                elif kind == "peer":
                    try:
                        self._on_peer_readable(conn)
                    except Exception:  # noqa: BLE001
                        traceback.print_exc()
                        self._safe(lambda c=conn: self._drop_peer(c))
                elif kind == "wake":
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                    # The loop is awake: callers can skip wake bytes until
                    # it disarms again right before the next blocking
                    # select (the loop-top drain picks their work up).
                    with self._inbox_lock:
                        self._wake_armed = True
                    self._drain_inbox()
                elif kind == "worker":
                    # Never let a malformed message kill the event thread; a
                    # worker whose channel is broken is treated as dead.
                    try:
                        self._on_worker_readable(conn)
                    except Exception:  # noqa: BLE001
                        traceback.print_exc()
                        self._safe(lambda c=conn: self._on_worker_death(c))
        # cleanup
        self._safe(self.flush_task_events)  # don't lose the last window
        self._safe(self.flush_trace_spans)
        self._safe(self.flush_profile_samples)
        for conn in list(self._workers.values()):
            try:
                conn.send({"t": "shutdown"})
                conn.sock.close()
            except OSError:
                pass
        for peer in list(self._peers.values()):
            try:
                peer.sock.close()
            except OSError:
                pass
        for p in self._procs:
            try:
                p.terminate()
            except OSError:
                pass
        try:
            self._listener.close()
            os.unlink(self.socket_path)
        except OSError:
            pass
        if self._tcp_listener is not None:
            try:
                self._tcp_listener.close()
            except OSError:
                pass
        if self._pull_manager is not None:
            self._pull_manager.close()
        if self._data_server is not None:
            self._data_server.close()
        store = self._store  # unguarded-ok: shutdown; data plane closed above
        if store is not None:
            try:
                store.close()
            except Exception:  # noqa: BLE001
                pass

    def _safe(self, fn):
        try:
            fn()
        except Exception:  # noqa: BLE001
            traceback.print_exc()

    def _drain_inbox(self):
        while True:
            with self._inbox_lock:
                if not self._inbox:
                    return
                fn = self._inbox.popleft()
            self._safe(fn)

    def _next_timer_delay(self):
        if not self._timers:
            return 0.5
        return max(0.0, self._timers[0][0] - time.monotonic())

    def add_timer(self, delay: float, cb: Callable):
        heapq.heappush(
            self._timers, (time.monotonic() + delay, next(self._timer_seq), cb)
        )

    def _accept(self, listener):
        try:
            sock, _ = listener.accept()
        except OSError:
            return
        sock.setblocking(True)
        if listener is self._tcp_listener:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        # Starts as a worker conn; a peer_hello / driver_hello first message
        # re-tags it (peers are other raylets, drivers are remote clients).
        conn = _WorkerConn(sock, profile="cpu")
        self._workers[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, ("worker", conn))

    _drain_frames = staticmethod(protocol.drain_frames)

    # ---- batched drain context ----
    # A frame train drained from one socket wakeup is handled under this
    # context: per-frame actor pumps collapse into one pump per actor and
    # per-frame replies into one coalesced sendall per conn, AFTER the whole
    # train is processed (one schedule pass — the _need_schedule flag — was
    # already per-batch).

    def _begin_drain(self):
        self._drain_depth += 1

    def _end_drain(self):
        self._drain_depth -= 1
        if self._drain_depth:
            return
        while self._pending_pumps:
            _, actor = self._pending_pumps.popitem()
            self._safe(lambda a=actor: self._pump_actor(a))
        while self._pending_replies:
            _, (conn, msgs) = self._pending_replies.popitem()
            try:
                conn.send_many(msgs)
            except OSError:
                pass  # conn died mid-drain; its death path handles cleanup
        # Task-event export rides the drain cadence: a burst that fills the
        # batch threshold ships now instead of waiting out the flush timer.
        if len(self._task_event_buf) >= config.task_event_batch_max:
            self.flush_task_events()

    def _queue_reply(self, conn: _WorkerConn, msg: dict):
        """Reply to a worker request: coalesced per drain, direct otherwise."""
        if self._drain_depth:
            entry = self._pending_replies.get(id(conn))
            if entry is None:
                self._pending_replies[id(conn)] = (conn, [msg])
            else:
                entry[1].append(msg)
        else:
            conn.send(msg)

    def _request_pump(self, actor: "_ActorState"):
        if self._drain_depth:
            self._pending_pumps[actor.actor_id] = actor
        else:
            self._pump_actor(actor)

    def _on_worker_readable(self, conn: _WorkerConn):
        """Buffered frame reader: ONE recv drains everything the kernel has
        for this socket (workers coalesce done bursts into frame trains),
        then every complete frame is handled — instead of one recv + one
        select() iteration per message."""
        try:
            data = conn.sock.recv(1 << 20)
        except OSError:
            data = b""
        if not data:
            self._on_worker_death(conn)
            return
        self._m_trains += 1
        self._m_train_bytes += len(data)
        if self._im is not None:
            self._im["train_bytes"].observe(len(data))
        conn.rbuf += data
        self._begin_drain()
        try:
            self._drain_frames(
                conn.rbuf,
                lambda msg: self._handle_worker_msg(conn, msg),
                lambda: self._workers.get(conn.sock) is conn)
        finally:
            self._end_drain()
        if self._workers.get(conn.sock) is conn:
            return
        # The conn left _workers mid-train: either it died (socket closed,
        # buffer moot) or a peer_hello promoted it to a raylet peer — any
        # remaining buffered frames belong to the peer protocol.
        try:
            kind, peer = self._sel.get_key(conn.sock).data
        except (KeyError, ValueError):
            return
        if kind == "peer" and conn.rbuf:
            peer.rbuf += conn.rbuf
            conn.rbuf = bytearray()
            self._begin_drain()
            try:
                self._drain_frames(
                    peer.rbuf,
                    lambda msg: self._handle_peer_msg(peer, msg),
                    lambda: self._peer_alive(peer))
            finally:
                self._end_drain()

    def _peer_alive(self, peer) -> bool:
        try:
            kind, cur = self._sel.get_key(peer.sock).data
        except (KeyError, ValueError):
            return False
        return kind == "peer" and cur is peer

    # --------------------------------------------------------------- workers

    def _profile_key(self, spec: TaskSpec) -> str:
        cached = getattr(spec, "_profile", None)
        if cached is not None:
            return cached
        needs_tpu = spec.resources.get("TPU", 0) > 0
        env = (spec.runtime_env or {}).get("env_vars") or {}
        if env:
            envkey = ",".join(f"{k}={v}" for k, v in sorted(env.items()))
            key = ("tpu|" if needs_tpu else "cpu|") + envkey
        else:
            key = "tpu" if needs_tpu else "cpu"
        spec._profile = key
        return key

    def _spawn_worker(self, profile: str):
        self._spawning[profile] = self._spawning.get(profile, 0) + 1
        env = dict(os.environ)
        env.update(self.worker_env)
        # Propagate the driver's import path: workers must resolve ray_tpu
        # (and the user's modules) no matter the cwd (reference ships the
        # driver's sys.path through the runtime env/worker command line).
        path_entries = [p for p in sys.path if p] + [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p
        ]
        seen = set()
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in path_entries if not (p in seen or seen.add(p))
        )
        if profile == "cpu" or profile.startswith("cpu|"):
            # CPU workers must not grab the TPU chip: a single process holds
            # the chip exclusively, so only TPU-profile workers may see it.
            # Force (not setdefault): the environment may pin JAX_PLATFORMS
            # to the TPU platform globally.
            env["JAX_PLATFORMS"] = "cpu"
            # The TPU-tunnel sitecustomize force-registers its PJRT platform
            # programmatically (overriding JAX_PLATFORMS); dropping its
            # trigger var keeps CPU workers off the chip entirely.
            env.pop("PALLAS_AXON_POOL_IPS", None)
        if "|" in profile:
            for kv in profile.split("|", 1)[1].split(","):
                k, v = kv.split("=", 1)
                env[k] = v
        env["RAY_TPU_WORKER_PROFILE"] = profile
        env["RAY_TPU_NODE_ID"] = self.node_id
        # Direct-transport fencing: the worker rejects direct hellos that
        # present an incarnation older than the node's at its spawn time
        # (a fenced node kills its workers, so this never goes stale).
        env["RAY_TPU_NODE_INCARNATION"] = str(self.incarnation)
        if self.cluster_mode:
            # lets the worker's direct-call listener bind TCP for callers
            # on peer nodes
            env["RAY_TPU_NODE_IP"] = self.node_ip
        cmd = [
            sys.executable,
            "-m",
            "ray_tpu.core.worker_main",
            "--socket",
            self.socket_path,
        ]
        if self.store_path:
            cmd += ["--store", self.store_path]
        stdout = stderr = None
        if self.cluster_mode and self.session_dir:
            # Per-worker combined log file, tailed to drivers (reference:
            # worker log files under the session dir + LogMonitor tailing,
            # `log_monitor.py:102`). Also keeps worker prints out of the
            # raylet's (undrained) stdout pipe.
            log_dir = os.path.join(self.session_dir, "logs")
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(
                log_dir, f"worker-{next(self._worker_log_seq):05d}.log")
            logf = open(log_path, "ab", buffering=0)
            stdout = stderr = logf
            self._worker_log_tails[log_path] = {"pos": 0, "pid": None}
            if not self._log_timer_armed:
                self._log_timer_armed = True
                self.add_timer(0.3, self._pump_worker_logs)
        proc = subprocess.Popen(cmd, env=env, cwd=os.getcwd(),
                                stdout=stdout, stderr=stderr)
        if stdout is not None:
            stdout.close()  # child keeps its copy
            self._worker_log_tails[log_path]["pid"] = proc.pid
            self._worker_log_tails[log_path]["proc"] = proc
            # log index outlives the tail entry (popped at worker death):
            # `ray_tpu logs` attribution + crash-forensics excerpts
            self._worker_log_pids[log_path] = proc.pid
            self._worker_log_by_pid[proc.pid] = log_path
        self._procs.append(proc)
        self._unregistered.append((proc, profile))
        if not self._health_timer_armed:
            self._health_timer_armed = True
            self.add_timer(config.health_check_period_s, self._health_check)

    def _pump_worker_logs(self):
        """Tail worker log files; push new complete lines to attached
        drivers (reference: LogMonitor → GCS pubsub → driver console)."""
        drivers = [c for c in self._workers.values()
                   if getattr(c, "state", None) == "driver"]
        for path, tail in list(self._worker_log_tails.items()):
            # Order matters: check liveness BEFORE reading, so "dead" means
            # the read below saw every byte the worker ever wrote (a final
            # flush between read and poll would otherwise be dropped when
            # the tail entry is popped).
            proc = tail.get("proc")
            worker_dead = proc is not None and proc.poll() is not None
            try:
                with open(path, "rb") as f:
                    f.seek(tail["pos"])
                    data = f.read()
            except OSError:
                self._worker_log_tails.pop(path, None)
                continue
            if not data:
                if worker_dead:
                    # fully drained a dead worker's file: stop tailing it
                    self._worker_log_tails.pop(path, None)
                continue
            # Ship complete lines; keep the partial tail for the next tick
            # unless the worker already exited (then flush everything).
            cut = len(data) if worker_dead else data.rfind(b"\n") + 1
            if cut <= 0:
                continue
            tail["pos"] += cut
            lines = data[:cut].decode("utf-8", "replace").splitlines()
            if drivers and lines:
                msg = {"t": "log", "node_id": self.node_id,
                       "pid": tail["pid"], "lines": lines}
                for conn in drivers:
                    try:
                        conn.send(msg)
                    except OSError:
                        pass
            if worker_dead:
                self._worker_log_tails.pop(path, None)
        if not self._shutdown:
            self.add_timer(0.3, self._pump_worker_logs)

    # ---- log files: list/tail over the protocol (`ray_tpu logs`) ----

    def _log_dir(self) -> str:
        return os.path.join(self.session_dir, "logs")

    def _logs_query(self, payload: dict):
        """Dispatch a logs node-query: ``{"action": "list"}`` or
        ``{"action": "tail", "name", "offset"?, "lines"?}``."""
        action = payload.get("action", "list")
        if action == "list":
            return self._list_logs()
        if action == "tail":
            return self._tail_log(payload.get("name"),
                                  payload.get("offset"),
                                  int(payload.get("lines", 100)))
        raise ValueError(f"unknown logs action {action!r}")

    def _list_logs(self) -> List[dict]:
        """Per-worker log files under ``session_dir/logs`` (cluster mode
        writes one per spawned worker; reference: ``ray logs`` over the
        session's log directory)."""
        out = []
        log_dir = self._log_dir()
        if not os.path.isdir(log_dir):
            return out
        for name in sorted(os.listdir(log_dir)):
            if not name.endswith(".log"):
                continue
            path = os.path.join(log_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append({"name": name, "size": st.st_size,
                        "mtime": st.st_mtime, "node_id": self.node_id,
                        "pid": self._worker_log_pids.get(path)})
        return out

    def _tail_log(self, name: Optional[str], offset: Optional[int] = None,
                  lines: int = 100) -> dict:
        """One read of a worker log file: the last ``lines`` lines when
        ``offset`` is None, else everything from ``offset`` (capped at
        1 MiB) — the returned ``offset`` feeds the next poll, which is
        how ``--follow`` streams without server-side state."""
        if not name or os.path.basename(name) != name:
            # basename equality rejects path traversal out of the log dir
            raise ValueError(f"bad log name {name!r}")
        path = os.path.join(self._log_dir(), name)
        size = os.path.getsize(path)  # OSError -> error reply
        with open(path, "rb") as f:
            if offset is None:
                f.seek(max(0, size - (1 << 20)))
                tail = f.read().splitlines()[-max(1, lines):]
                data = b"\n".join(tail) + (b"\n" if tail else b"")
                new_offset = size
            else:
                offset = max(0, min(int(offset), size))
                f.seek(offset)
                data = f.read(1 << 20)
                new_offset = offset + len(data)
        return {"name": name, "data": data.decode("utf-8", "replace"),
                "offset": new_offset, "size": size,
                "node_id": self.node_id}

    def _crash_log_excerpt(self, pid: Optional[int], n: int = 20) -> str:
        """The last ``n`` log lines of a (dead) worker, formatted for
        embedding in its failure message — crash forensics: the operator
        sees the traceback / faulthandler dump / OOM-killer line without
        hunting for the right file on the right node."""
        path = self._worker_log_by_pid.get(pid) if pid is not None else None
        if path is None:
            return ""
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(max(0, size - 65536))
                tail = f.read().decode("utf-8", "replace").splitlines()[-n:]
        except OSError:
            return ""
        if not tail:
            return ""
        return (f"\n--- last {len(tail)} line(s) of worker log "
                f"({os.path.basename(path)}) ---\n" + "\n".join(tail))

    # ---- memory monitor / worker killing (reference: MemoryMonitor
    # `src/ray/common/memory_monitor.h:52` + retriable-FIFO policy
    # `worker_killing_policy_retriable_fifo.cc`) ----

    def _memory_usage_fraction(self) -> float:
        path = config.memory_usage_file
        if path:
            try:
                with open(path) as f:
                    return float(f.read().strip())
            except (OSError, ValueError):
                return 0.0
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, v = line.split(":", 1)
                    info[k] = int(v.strip().split()[0])
            avail = info.get("MemAvailable", info.get("MemFree", 0))
            total = max(info.get("MemTotal", 1), 1)
            return 1.0 - avail / total
        except OSError:  # pragma: no cover — non-Linux
            return 0.0

    def _pick_oom_victim(self) -> Optional[_WorkerConn]:
        """Retriable-FIFO: prefer the LAST-started RETRIABLE task's worker
        (its retry costs the least lost work and is safe); else the
        last-started task's worker.  Leased workers executing DIRECT
        calls count too (their task rides _direct_running, not
        current_task) — the caller's channel EOF reconciles the kill
        through the ordinary retry path."""
        direct_task: Dict[_WorkerConn, TaskSpec] = {}
        for _conn, _spec in self._direct_running.values():
            direct_task.setdefault(_conn, _spec)

        def task_of(c: _WorkerConn) -> Optional[TaskSpec]:
            if c.state == "busy" and c.current_task is not None:
                return c.current_task
            if c.state == "leased":
                return direct_task.get(c)
            return None

        busy = [(c, t) for c in self._workers.values()
                if c.pid is not None and (t := task_of(c)) is not None]
        if not busy:
            return None
        retriable = [(c, t) for c, t in busy
                     if getattr(t, "retries_left", 0) > 0]
        pool = retriable or busy
        return max(pool, key=lambda ct:
                   getattr(ct[0], "task_start_time", 0.0))[0]

    def _memory_check(self):
        frac = self._memory_usage_fraction()
        if frac > config.memory_usage_threshold:
            victim = self._pick_oom_victim()
            if victim is not None:
                spec = victim.current_task
                sys.stderr.write(
                    f"[ray_tpu] memory usage {frac:.2f} > "
                    f"{config.memory_usage_threshold:.2f}: killing worker "
                    f"pid={victim.pid} running "
                    f"{spec.name if spec else '?'} (OOM prevention)\n")
                if spec is not None:
                    self._record_event(spec, "OOM_KILLED", pid=victim.pid)
                # the death path raises typed OutOfMemoryError (with the
                # crash-forensics excerpt) instead of a generic crash
                victim.oom_kill = True
                try:
                    os.kill(victim.pid, 9)
                except (ProcessLookupError, PermissionError):
                    pass
                # the normal worker-death path fails/retries the task
        if not self._shutdown:
            self.add_timer(config.memory_monitor_interval_s,
                           self._memory_check)

    def _health_check(self):
        """Reap workers that died before registering (e.g. import failure) so
        the scheduler doesn't wait forever on a phantom spawn (reference:
        WorkerPool startup-token timeouts, `worker_pool.cc`)."""
        alive = []
        for proc, profile in self._unregistered:
            if proc.poll() is not None:
                self._spawning[profile] = max(0, self._spawning.get(profile, 0) - 1)
                sys.stderr.write(
                    f"[ray_tpu] worker (profile={profile}) exited with code "
                    f"{proc.returncode} before registering — check worker "
                    "environment/imports\n"
                )
            else:
                alive.append((proc, profile))
        self._unregistered = alive
        self._schedule()
        if self._unregistered or self._spawning:
            self.add_timer(config.health_check_period_s, self._health_check)
        else:
            self._health_timer_armed = False

    def _get_idle_worker(self, profile: str) -> Optional[_WorkerConn]:
        pool = self._idle.get(profile)
        while pool:
            conn = pool.popleft()
            if conn.sock in self._workers:
                return conn
        return None

    def _return_worker(self, conn: _WorkerConn):
        conn.state = "idle"
        conn.current_task = None
        self._idle.setdefault(conn.profile, deque()).append(conn)

    def _on_worker_death(self, conn: _WorkerConn):
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._workers.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass
        for cancel in list(conn.request_cancels.values()):
            self._safe(cancel)
        conn.request_cancels.clear()
        self._release_conn_lease(conn)
        self._release_conn_holds(conn)
        # crash forensics: the dead worker's log tail rides the error so
        # ActorDiedError / WorkerCrashedError carry the actual traceback
        # or faulthandler dump (cluster mode; single-node workers share
        # the driver's stdio and have no file)
        excerpt = self._crash_log_excerpt(conn.pid)
        if self._direct_running:
            for tid in [t for t, rec in self._direct_running.items()
                        if rec[0] is conn]:
                del self._direct_running[tid]
        oom = conn.oom_kill
        if conn.actor_id is not None:
            reason = ("worker OOM-killed by the memory monitor" if oom
                      else "worker process died") + excerpt
            self._on_actor_death(conn.actor_id, reason)
        else:
            interrupted = list(conn.inflight.values()) or (
                [conn.current_task] if conn.current_task is not None else []
            )
            conn.inflight.clear()
            for spec in interrupted:
                self._release_task_resources(spec)
                if spec.retries_left > 0:
                    # OOM kills count against the SAME retry budget as
                    # crashes (reference: OOM-killed tasks retried with
                    # the task's budget, memory_monitor retry semantics)
                    spec.retries_left -= 1
                    self._record_event(spec, "RETRYING", worker_died=True,
                                       oom=oom)
                    self._enqueue_ready(spec)
                elif oom:
                    err = OutOfMemoryError(
                        f"worker (pid={conn.pid}) was OOM-killed by the "
                        f"memory monitor while running {spec.name}"
                        f"{excerpt}")
                    for oid in spec.return_ids():
                        self._object_error(oid, err)
                    self._record_event(spec, "FAILED", worker_died=True,
                                       oom=True,
                                       error=self._err_summary(err))
                else:
                    err = WorkerCrashedError(
                        f"worker (pid={conn.pid}) died while running "
                        f"{spec.name}{excerpt}"
                    )
                    for oid in spec.return_ids():
                        self._object_error(oid, err)
                    self._record_event(spec, "FAILED", worker_died=True,
                                       error=self._err_summary(err))
        self._schedule()

    # --------------------------------------------------------------- messages

    def _handle_worker_msg(self, conn: _WorkerConn, msg: dict):
        # Hot-path types first: a drained train is almost entirely done /
        # request / submit frames (the rest are connection lifecycle).
        self._m_frames += 1
        t = msg["t"]
        if t == "done":
            self._on_task_done(conn, msg)
            return
        if t == "request":
            self._handle_request(conn, msg)
            return
        if t == "submit":
            self.submit_task(msg["spec"])
            return
        if t == "direct_done":
            # completion bookkeeping for a call that travelled the direct
            # worker→worker channel (results already reached the caller)
            self._on_direct_done(conn, msg)
            return
        if t == "direct_running":
            self._on_direct_running(conn, msg)
            return
        if t == "direct_notes":
            # one coalesced train of direct_running/direct_done notes
            # (burst mode): apply in order — per-note bookkeeping matches
            # the unbatched frames, the batch just amortizes the
            # socket/dispatch cost across the callee's drained train.
            # Coalesced-pair elision: a call whose RUNNING and DONE notes
            # ride the SAME train already finished — its RUNNING note
            # would only arm the cancel seam (moot) and a timeline row
            # the FINISHED event supersedes, so skip it.  This halves
            # the event-thread work per burst call; with the kill switch
            # off notes arrive unbatched and keep full RUNNING fidelity.
            notes = msg["notes"]
            done_ids = {note["spec"].task_id for note in notes
                        if note.get("t") != "direct_running"}
            for note in notes:
                if note.get("t") == "direct_running":
                    if note["spec"].task_id not in done_ids:
                        self._on_direct_running(conn, note)
                else:
                    self._on_direct_done(conn, note)
            return
        if t == "ping":
            # Liveness probe (GCS direct probe, or a peer relaying an
            # indirect one): echo identity + incarnation so a recycled
            # port or a stale incarnation never passes for liveness.
            try:
                conn.send({"t": "pong", "node_id": self.node_id,
                           "incarnation": self.incarnation})
            except OSError:
                pass
            return
        if t == "peer_hello":
            # Another raylet dialed us: promote the conn to a peer channel
            # — unless it presents a fenced incarnation (a resurrected
            # partitioned node must re-register before its frames count).
            inc = msg.get("incarnation")
            if inc is not None and not self._peer_fence_ok(msg["node_id"],
                                                           inc):
                self._workers.pop(conn.sock, None)
                try:
                    self._sel.unregister(conn.sock)
                except (KeyError, ValueError):
                    pass
                try:
                    conn.sock.close()
                except OSError:
                    pass
                return
            peer = _PeerConn(conn.sock, msg["node_id"])
            self._workers.pop(conn.sock, None)
            self._sel.modify(conn.sock, selectors.EVENT_READ, ("peer", peer))
            self._peers.setdefault(msg["node_id"], peer)
            return
        if t == "driver_hello":
            conn.state = "driver"
            conn.send({"t": "hello_reply", "node_id": self.node_id,
                       "store_path": self.store_path,
                       "session_dir": self.session_dir,
                       "gcs_address": self.gcs_address})
            return
        if t == "register":
            conn.worker_id = msg["worker_id"]
            conn.pid = msg["pid"]
            conn.profile = msg.get("profile", "cpu")
            conn.direct_addr = msg.get("direct_addr")
            self._spawning[conn.profile] = max(
                0, self._spawning.get(conn.profile, 0) - 1
            )
            self._unregistered = [
                (p, prof) for p, prof in self._unregistered if p.pid != conn.pid
            ]
            self._return_worker(conn)
            self._schedule()
        elif t == "requeue":
            # the worker's current task blocked (nested get/wait) with
            # unstarted batch members queued behind it — take them back so
            # they can run elsewhere instead of waiting out the block.
            # Use the raylet-side spec objects (conn.inflight) — they carry
            # the batch accounting the wire copies don't.
            for wire_spec in msg["specs"]:
                spec = conn.inflight.pop(wire_spec.task_id, None)
                if spec is None:
                    continue  # already completed/raced
                self._release_task_resources(spec)
                self._record_event(spec, "REQUEUED")
                self._enqueue_ready(spec)
            self._schedule()
        elif t == "stream_item":
            self._on_stream_item(msg)
        elif t == "checkpoint":
            self._on_actor_checkpoint(conn, msg)
        elif t == "ref_events":
            self.apply_ref_events(msg["events"], conn)
        elif t == "spans":
            # worker span batch (request-flow tracing) -> GCS trace table
            self._trace_ingest(msg["spans"], msg.get("dropped", 0))
        elif t == "profile_samples":
            # worker folded-stack batch (continuous profiling) -> GCS
            # profile table on the next flush tick
            self._profile_ingest(msg["samples"], msg.get("dropped", 0))
        elif t == "metric_points":
            # worker metric delta-point batch (time-series export) -> GCS
            # metrics table on the next internal-metrics tick
            self._metric_points_ingest(msg["points"], msg.get("dropped", 0))
        elif t == "stack_reply":
            # a worker answered a live stack-dump request (ray_tpu stack)
            self._on_stack_reply(conn, msg)

    def _on_task_done(self, conn: _WorkerConn, msg: dict):
        tid = msg.get("task_id")
        spec = conn.inflight.pop(tid, None) if tid is not None else None
        if spec is None:
            spec = conn.current_task
        if spec is None:
            return
        trace_t0 = time.time() if self._spec_traced(spec) else 0.0
        # Clear ALL bookkeeping for this attempt up front — a retry
        # re-enters via _enqueue_ready below and must register fresh state,
        # not have its new entries popped by this (finished) attempt.
        if conn.current_task is spec:
            conn.current_task = None
        actor = (self._actors.get(conn.actor_id)
                 if conn.actor_id is not None else None)
        if actor is not None:
            actor.inflight.pop(spec.task_id, None)
        task_failed = not msg["ok"]
        # Actors HOLD their resources while alive (released on death); every
        # other task releases at completion.
        if not (spec.kind == ACTOR_CREATION_TASK and not task_failed):
            self._release_task_resources(spec)
        retrying = (task_failed and spec.retries_left > 0
                    and msg.get("retryable", True))
        if not retrying:
            if task_failed:
                err = msg["error"]
                for oid in spec.return_ids():
                    self._object_error(oid, err)
                self._record_event(spec, self._failure_state(err),
                                   error=self._err_summary(err))
            else:
                inline: Dict[str, bytes] = msg.get("inline", {})
                stored: List[str] = msg.get("stored", [])
                sizes: Dict[str, int] = msg.get("sizes", {})
                contains: Dict[str, list] = msg.get("contains", {})
                for hex_id, blob in inline.items():
                    self._object_inline(ObjectID.from_hex(hex_id), blob,
                                        contains=contains.get(hex_id))
                for hex_id in stored:
                    oid = ObjectID.from_hex(hex_id)
                    self._obj(oid).size = sizes.get(hex_id, 0)
                    self._object_in_store(oid,
                                          contains=contains.get(hex_id))
                    # eager availability: push a secondary copy of a big
                    # (or explicitly flagged) result while it is hot
                    self._maybe_replicate(oid, force=spec.replicate,
                                          trace_ctx=spec.trace_ctx)
                self._record_event(spec, "FINISHED")
            if trace_t0:
                # result hop: done-frame processing + sealing the return
                # objects (waiter wakeups included)
                self._trace_hop(spec, "raylet.result", trace_t0,
                                status="ERROR" if task_failed else "OK")
        # worker back to pool / actor next call
        if spec.kind == ACTOR_CREATION_TASK:
            if task_failed:
                # creation failed: free the worker; a retry (if any) spawns
                # on a fresh lease, final failure kills the actor.
                conn.actor_id = None
                if actor is not None:
                    actor.conn = None
                if not retrying:
                    self._on_actor_death(spec.actor_id, "creation task failed",
                                         allow_restart=False)
                self._return_worker(conn)
            else:
                actor.state = "alive"
                actor.conn = conn
                actor.node_id = None  # executing locally, whatever was tried
                conn.state = "actor"
                # sync/async execution model, reported by the worker after
                # instantiation — gates call pipelining (admit_limit)
                actor.async_actor = bool(msg.get("async_actor"))
        elif actor is not None:
            if not conn.inflight:
                conn.state = "actor"
        else:
            # batched dispatch: the worker still has queued batch members;
            # it returns to the pool only when the last one completes.
            if not conn.inflight:
                self._return_worker(conn)
        if retrying:
            spec.retries_left -= 1
            self._record_event(spec, "RETRYING")
            # Actor-task retries must rejoin the actor's queue, not land on
            # an arbitrary idle worker with no actor instance.
            self._enqueue_ready(spec)
        if actor is not None and actor.state == "alive":
            # Deferred under a batched drain: N dones from one wakeup pump
            # the actor ONCE (one coalesced dispatch train) instead of N
            # single-message sendalls.
            self._request_pump(actor)
        self._schedule()

    # ---------------------------------------------- direct transport broker
    # (core/direct.py): the raylet's residual roles on the direct path —
    # address/lease/incarnation broker, completion bookkeeper, and the
    # fence that keeps retries exactly-once across actor restarts.

    def direct_call_info(self, actor_id: ActorID) -> Optional[dict]:
        """Broker a direct channel to an actor's worker: address + PR 8
        incarnation + restart generation.  None = stay on the relayed
        path (actor not alive here, no listener, or direct disabled)."""
        if not config.direct_calls or self._draining:
            return None
        actor = self._actors.get(actor_id)
        if actor is None or actor.state != "alive":
            return None
        if actor.node_id is not None and actor.node_id != self.node_id:
            # forwarded actor: hand out the exec-side listener the
            # creation xdone piggybacked (generation stays OURS — the
            # owner's restart counter is the fencing authority)
            if actor.direct_info is None:
                return None
            info = dict(actor.direct_info)
            info["generation"] = actor.generation
            return info
        conn = actor.conn
        if conn is None or not conn.direct_addr:
            return None
        return {"addr": conn.direct_addr, "generation": actor.generation,
                "incarnation": self.incarnation, "node_id": self.node_id,
                "pid": conn.pid}

    def acquire_direct_lease(self, spec: TaskSpec) -> Optional[dict]:
        """Lease an idle pool worker to a caller for direct normal-task
        submission (reference: worker lease reuse).  Grants only when the
        node is otherwise quiet — queued work always wins the pool — and
        holds the spec's resource shape until release/death."""
        if (not config.direct_calls or self._draining
                or self._ready_queue or self._waiting):
            return None
        need = spec.resources or {}
        if not _fits(self.resources_available, need):
            return None
        profile = self._profile_key(spec)
        conn = self._get_idle_worker(profile)
        if conn is None:
            return None
        if not conn.direct_addr:
            self._return_worker(conn)
            return None
        _acquire(self.resources_available, need)
        lease_id = f"lease-{next(self._lease_seq)}"
        conn.state = "leased"
        conn.current_task = None
        conn.lease = {"id": lease_id, "need": need}
        self._leases[lease_id] = conn
        try:
            # hand the worker the lease token: its DirectServer rejects
            # lease hellos that don't present exactly this id, so a
            # dialer can never execute tasks outside raylet accounting
            conn.send({"t": "direct_lease", "lease_id": lease_id})
        except OSError:
            # worker died under us: undo the grant, decline
            self._leases.pop(lease_id, None)
            conn.lease = None
            _release(self.resources_available, need)
            return None
        self._m_direct_leases += 1
        return {"addr": conn.direct_addr, "lease_id": lease_id,
                "generation": 0, "incarnation": self.incarnation,
                "node_id": self.node_id, "pid": conn.pid}

    def release_direct_lease(self, lease_id: str):
        conn = self._leases.pop(lease_id, None)
        if conn is None:
            return
        _release(self.resources_available, conn.lease["need"])
        conn.lease = None
        if conn.sock in self._workers:  # still alive: back to the pool
            try:
                conn.send({"t": "direct_lease", "lease_id": None})
            except OSError:
                pass  # imminent EOF reaps it
            self._return_worker(conn)
            self._schedule()

    def _release_conn_lease(self, conn: _WorkerConn):
        """Worker died while leased: give its resources back (the caller's
        channel EOF reconciles the in-flight tasks via the normal path)."""
        if conn.lease is None:
            return
        self._leases.pop(conn.lease["id"], None)
        _release(self.resources_available, conn.lease["need"])
        conn.lease = None

    def _broadcast_direct_fence(self, actor_ids=None, node_id=None):
        """Tell direct callers to tear down channels for these actors (or
        this whole node) NOW — a partitioned callee produces no socket
        EOF, so blocked callers would otherwise wait out the freeze
        instead of reconciling through the raylet."""
        msg = {"t": "direct_fence",
               "actor_ids": list(actor_ids or ()), "node_id": node_id}
        if self.direct_fence_cb is not None:
            self._safe(lambda: self.direct_fence_cb(msg))
        for conn in list(self._workers.values()):
            if not conn.uses_direct:
                continue
            try:
                conn.send(msg)
            except OSError:
                pass

    def _on_direct_running(self, conn: _WorkerConn, msg: dict):
        """In-flight visibility for direct calls (timeline/state API);
        the dispatch itself never touched this raylet.  Also the
        cancel/deadline seam for direct work: record who executes it
        (cancel frames route to that worker's control socket) and its
        fan-out edge (nested submits reap with their parent)."""
        spec = msg["spec"]
        self._record_event(spec, "RUNNING", direct=True,
                           pid=conn.pid)
        self._note_child(spec)
        self._direct_running[spec.task_id] = (conn, spec)
        if len(self._direct_running) > 8192:  # missed dones: age out
            self._direct_running.pop(next(iter(self._direct_running)))
        flag = self._cancelled_flag(spec)
        if flag is not None:
            # the note raced a cancel/deadline fan-out that already
            # walked the children index: reap it now that we know who
            # executes it
            self._note_cancelled(spec.task_id, flag)
            try:
                conn.send({"t": "cancel", "task_id": spec.task_id,
                           "deadline": flag})
            except OSError:
                self._on_worker_death(conn)

    def _on_direct_done(self, conn: Optional[_WorkerConn], msg: dict):
        spec: TaskSpec = msg["spec"]
        self._m_direct_dones += 1
        actor = (self._actors.get(spec.actor_id)
                 if spec.actor_id is not None else None)
        if actor is not None and actor.foreign_owner is not None:
            # exec side of a forwarded actor: keep the store bytes
            # registered here, relay the completion to the OWNER raylet —
            # it owns the object table entries and the task events.
            for h in msg.get("stored") or ():
                oid = ObjectID.from_hex(h)
                if self._object_status(oid) not in ("inline", "store",
                                                    "error"):
                    self._obj(oid).size = (msg.get("sizes") or {}).get(h, 0)
                    self._object_in_store(oid)
            peer = self._get_peer(actor.foreign_owner)
            if peer is not None:
                relay = {k: v for k, v in msg.items() if k != "t"}
                try:
                    peer.send({"t": "xdirect_done", "node_id": self.node_id,
                               "msg": relay})
                except OSError:
                    self._drop_peer(peer)
            return
        self._apply_direct_done(msg, store_node=None)

    def _handle_xdirect_done(self, msg: dict):
        self._apply_direct_done(msg["msg"], store_node=msg["node_id"])

    def _apply_direct_done(self, msg: dict, store_node: Optional[str]):
        """Owner-side bookkeeping for a direct completion: seal/error the
        return objects (idempotent — a raylet-path retry may already have
        resolved them), retain lineage for lease tasks, count the task
        event.  tracked=True arms the ordinary grace-free path, so a
        result whose caller already dropped every ref still gets swept."""
        spec: TaskSpec = msg["spec"]
        self._direct_running.pop(spec.task_id, None)
        keep_lineage = (spec.kind == NORMAL_TASK
                        and self._lineage_count < config.max_lineage_entries)
        if msg["ok"]:
            contains = msg.get("contains") or {}
            sizes = msg.get("sizes") or {}
            for h, blob in (msg.get("inline") or {}).items():
                oid = ObjectID.from_hex(h)
                if self._object_status(oid) in ("inline", "store", "error"):
                    continue
                st = self._obj(oid)
                st.tracked = True
                if keep_lineage and st.creating_spec is None:
                    st.creating_spec = spec
                    self._lineage_count += 1
                self._object_inline(oid, blob, contains=contains.get(h))
            for h in msg.get("stored") or ():
                oid = ObjectID.from_hex(h)
                if self._object_status(oid) in ("inline", "store", "error"):
                    continue
                st = self._obj(oid)
                st.tracked = True
                st.size = max(st.size, sizes.get(h, 0))
                if keep_lineage and st.creating_spec is None:
                    st.creating_spec = spec
                    self._lineage_count += 1
                if store_node is not None and store_node != self.node_id:
                    # bytes live in the exec node's store: register the
                    # location; a local get pulls over the data plane
                    st.status = "remote"
                    if store_node not in st.locations:
                        st.locations.append(store_node)
                    self._object_ready(oid)
                else:
                    self._object_in_store(oid, contains=contains.get(h))
                    self._maybe_replicate(oid, force=spec.replicate,
                                          trace_ctx=spec.trace_ctx)
            dur = msg.get("dur")
            if dur is not None:
                # callee-stamped exec duration: keeps timeline latency
                # visible even when the paired RUNNING note was elided
                # by the coalesced-train fast path
                self._record_event(spec, "FINISHED", direct=True,
                                   exec_s=dur)
            else:
                self._record_event(spec, "FINISHED", direct=True)
        else:
            err = msg.get("error")
            for oid in spec.return_ids():
                if self._object_status(oid) in ("inline", "store", "error"):
                    continue
                self._object_error(oid, err)
            self._record_event(spec, self._failure_state(err), direct=True,
                               error=self._err_summary(err))

    # --------------------------------------------------------------- cluster

    def _pending_demand_shapes(self, cap: int = 256):
        """Aggregate resource shapes of queued tasks that cannot run with
        current availability — the autoscaler's scale-up signal."""
        shapes: Dict[tuple, int] = {}
        for spec in itertools.islice(self._ready_queue, cap):
            need = spec.resources or {}
            if _fits(self.resources_available, need):
                continue
            key = tuple(sorted(need.items()))
            shapes[key] = shapes.get(key, 0) + 1
        return [(dict(k), n) for k, n in shapes.items()]

    def _apply_registration(self, snapshot):
        """Digest a register_node reply: adopt the incarnation the GCS
        assigned this node and refresh the peer membership view."""
        for info in snapshot or ():
            if info["node_id"] == self.node_id:
                self.incarnation = info.get("incarnation", self.incarnation)
            elif info["alive"]:
                self._cluster_nodes[info["node_id"]] = info

    def _register_with_gcs(self):
        # Proposing the incarnation we last held keeps the assigned one
        # strictly ABOVE every fence watermark peers may hold for us even
        # when the GCS lost its counters (restart without persistence).
        self._apply_registration(self.gcs.register_node(
            self.node_id, (self.node_ip, self.tcp_port),
            self.resources_total, store_path=self.store_path,
            hostname=socket.gethostname(),
            labels=self.node_labels, data_port=self.data_port,
            incarnation=self.incarnation))

    def _heartbeat(self):
        if self._drained:
            return  # drained: this node is retired, stop asserting liveness
        try:
            ok = self.gcs.heartbeat(self.node_id, self.resources_available,
                                    queue_len=len(self._ready_queue),
                                    pending_shapes=self._pending_demand_shapes(),
                                    incarnation=self.incarnation)
            if ok == "fenced":
                # This incarnation was declared dead (partition healed,
                # long stall): split-brain guard — kill the local workers
                # and come back as a fresh incarnation.
                self._on_fenced()
            elif not ok:
                # GCS lost track of us (restart): plain re-register.
                self._register_with_gcs()
        except (ConnectionError, TimeoutError, OSError):
            pass
        if not self._shutdown and not self._drained:
            self.add_timer(config.gcs_heartbeat_interval_s, self._heartbeat)

    def _on_fenced(self):
        """The GCS rejected this node's incarnation: some failure detector
        declared it dead and the cluster may already have restarted its
        actors and reconstructed its objects elsewhere.  The ONLY safe
        continuation is to kill every local worker (so no stale actor
        instance or in-flight task can double-execute side effects or
        publish stale results) and re-register under a fresh incarnation
        (reference: a fenced raylet restarts; here the process survives
        but its execution state does not)."""
        sys.stderr.write(
            f"[ray_tpu] node {self.node_id[:8]}: incarnation "
            f"{self.incarnation} was fenced (declared dead) — killing "
            "local workers and re-registering\n")
        for proc in self._procs:
            try:
                proc.kill()
            except OSError:
                pass
        # Worker deaths flow back through the normal socket-EOF path
        # (task failures/retries, actor restarts per budget) — with a
        # fresh incarnation those re-assertions are accepted again.
        try:
            self._register_with_gcs()
        except (ConnectionError, TimeoutError, OSError):
            return  # next heartbeat retries the re-register
        # Re-publish surviving local store objects: the death declaration
        # pruned them from the directory, but the bytes are still valid.
        for oid, st in self._objects.items():
            if st.status == "store":
                self._gcs_post("add_object_location", oid.hex(),
                               self.node_id, st.size or 0,
                               incarnation=self.incarnation)

    def _peer_fence_ok(self, node_id: str, incarnation: int) -> bool:
        """Data-server handshake / peer-hello check (any thread): reject a
        peer presenting an incarnation that was declared dead.  Unknown
        nodes are accepted — they may simply not have registered yet from
        this node's point of view."""
        fenced = self._fenced.get(node_id)
        if fenced is not None and incarnation <= fenced:
            self._m_fenced_frames += 1  # unguarded-ok: monotonic stat counter
            return False
        return True

    def _relay_probe(self, data: dict):
        """Indirect liveness probe: the GCS asked THIS raylet to ping a
        suspect peer it cannot reach itself (covers an asymmetric
        GCS<->node partition where peers still can).  The blocking dial
        runs on a throwaway thread — never on the event loop."""
        gcs = self.gcs

        def run():
            ok = protocol.liveness_ping(
                data["address"], data["target"], data["incarnation"],
                config.gcs_probe_timeout_s)
            try:
                gcs.probe_report(data["token"], ok)
            except (ConnectionError, TimeoutError, OSError):
                pass  # GCS gone: its waiter times out on its own

        threading.Thread(target=run, name="probe-relay",
                         daemon=True).start()

    # ------------------------------------------------------ graceful drain
    # (reference: the autoscaler's DrainNode RPC before instance
    # termination.)  The GCS flipped this node's `draining` flag before
    # pushing node_drain, so no NEW placement lands here; the raylet then
    # (1) checkpoint-and-relocates checkpointable actors, (2) pushes
    # sole-copy store objects to surviving nodes via the replication path,
    # (3) waits for running tasks — all bounded by the drain deadline —
    # and reports drain_complete, which retires the node with ZERO
    # reconstructions.

    def _on_drain_request(self, timeout_s: float):
        if self._draining or self._shutdown:
            return
        self._draining = True
        self._drain_deadline = time.monotonic() + max(0.5, timeout_s)
        self._drain_stats = {"objects_migrated": 0, "actors_relocated": 0,
                             "deadline_hit": 0}
        sys.stderr.write(
            f"[ray_tpu] node {self.node_id[:8]}: draining "
            f"(deadline {timeout_s:.1f}s)\n")
        # Checkpointable actors executing here: final checkpoint + graceful
        # exit; the restart re-places elsewhere (the GCS skips draining
        # nodes) and restores warm.  Non-checkpointable actors ride the
        # node-death path at completion like a crash would, minus the
        # detection latency.
        for aid, actor in list(self._actors.items()):
            if (actor.conn is not None
                    and actor.creation_spec.checkpoint_interval > 0):
                self._drain_stats["actors_relocated"] += 1
                self.kill_actor(aid, no_restart=False)
        self._drain_push_objects()
        self.add_timer(0.2, self._drain_tick)

    def _drain_sole_copies(self) -> List[ObjectID]:
        """Local store objects the directory lists no OTHER holder for —
        the set whose bytes die with this node unless migrated."""
        held = [oid for oid, st in self._objects.items()
                if st.status == "store"]
        if not held:
            return []
        locs = self._gcs_err_ok(self.gcs.get_object_locations_batch,
                                [o.hex() for o in held])
        if locs is _GCS_ERR:
            return held  # can't tell: keep pushing until the GCS answers
        sole = []
        for oid in held:
            nodes = set((locs or {}).get(oid.hex(), {}).get("nodes", ()))
            nodes.discard(self.node_id)
            if not nodes:
                sole.append(oid)
        return sole

    def _drain_push_objects(self, sole: Optional[List[ObjectID]] = None):
        now = time.monotonic()
        if sole is None:
            sole = self._drain_sole_copies()
        for oid in sole:
            st = self._objects.get(oid)
            if st is None or st.status != "store":
                continue
            last = self._drain_push_at.get(oid)
            if last is not None and now - last < 1.0:
                continue  # a push is in flight; give the pull a second
            if last is not None:
                # the previous push never registered a copy (lost frame,
                # dead target): the directory says we are still the sole
                # holder, so every recorded replica is unconfirmed — clear
                # them so the retry may pick the same target again
                st.replicas = []
            self._drain_push_at[oid] = now
            if oid not in self._drain_pushed:
                self._drain_pushed.add(oid)
                self._drain_stats["objects_migrated"] += 1
            # force one extra copy regardless of size threshold; the
            # drain tick re-pushes if the target never registered it
            st.replicated = False
            self._replicate_object(oid, st, 1)

    def _drain_tick(self):
        if self._shutdown or not self._draining or self._drained:
            return
        tasks_running = any(c.inflight for c in self._workers.values())
        actors_here = any(a.conn is not None
                          for a in self._actors.values())
        sole = self._drain_sole_copies()
        deadline_hit = time.monotonic() >= self._drain_deadline
        if (sole or tasks_running or actors_here
                or self._ready_queue) and not deadline_hit:
            if sole:
                self._drain_push_objects(sole)  # re-push stragglers
            self.add_timer(0.2, self._drain_tick)
            return
        if deadline_hit and (sole or tasks_running or actors_here):
            self._drain_stats["deadline_hit"] = 1
        self._finish_drain()

    def _finish_drain(self):
        self._drained = True
        stats = dict(self._drain_stats)
        sys.stderr.write(
            f"[ray_tpu] node {self.node_id[:8]}: drain complete {stats}\n")
        self._gcs_safe(self.gcs.drain_complete, self.node_id, stats)
        # A drained node is retired: shut the raylet down (the autoscaler
        # terminates the instance; in tests the process exits cleanly).
        self._shutdown = True
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass
        if self.on_fatal is not None:
            self._safe(self.on_fatal)

    def _gcs_push(self, event: str, data):
        """Runs on the GCS client/reader thread — hop to the event loop."""
        self.call_async(self._on_gcs_event, event, data)

    def _on_gcs_lost(self):
        """GCS connection dropped (reader thread): with reconnect enabled
        (GCS fault tolerance — the GCS restarts with persisted tables),
        retry dialing it; otherwise the node is partitioned from the
        control plane — shut down rather than orphan the worker tree."""
        if self._shutdown:
            return
        if config.gcs_reconnect_timeout_s > 0 and self.gcs_address:
            threading.Thread(target=self._gcs_reconnect_loop,
                             name="gcs-reconnect", daemon=True).start()
            return
        sys.stderr.write(
            f"[ray_tpu] node {self.node_id[:8]}: GCS connection lost — "
            "shutting down\n")
        self._shutdown = True
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass
        if self.on_fatal is not None:
            self._safe(self.on_fatal)

    def _gcs_reconnect_loop(self):
        """Reader-thread side: dial the (restarted) GCS until the timeout
        under the unified jittered-exponential backoff, then hand over to
        the event loop to re-register and re-publish this node's object
        locations."""
        deadline = time.monotonic() + config.gcs_reconnect_timeout_s
        sys.stderr.write(
            f"[ray_tpu] node {self.node_id[:8]}: GCS connection lost — "
            f"reconnecting for up to {config.gcs_reconnect_timeout_s:.0f}s\n")
        # De-synchronize the herd: every raylet's reader thread saw the
        # GCS socket die at the same instant; without this full-span
        # stagger they all dial — and then re-register, re-subscribe, and
        # re-publish their whole object directories — in lockstep the
        # moment the port reopens.
        time.sleep(min(self._retry_policy.stagger(
            config.gcs_reconnect_stagger_s),
            max(0.0, deadline - time.monotonic())))
        attempt = 0
        while time.monotonic() < deadline and not self._shutdown:
            try:
                new_gcs = GcsClient(self.gcs_address,
                                    push_handler=self._gcs_push,
                                    on_disconnect=self._on_gcs_lost)
                break
            except (ConnectionError, OSError):
                time.sleep(min(self._retry_policy.delay(attempt),
                               max(0.0, deadline - time.monotonic())))
                attempt += 1
        else:
            if not self._shutdown:
                config.gcs_reconnect_timeout_s = 0.0  # no second chance
                self._on_gcs_lost()
            return
        self.call_async(self._after_gcs_reconnect, new_gcs)

    def _after_gcs_reconnect(self, new_gcs):
        """Event loop: swap the client in, re-register (node table is soft
        state), resubscribe, and re-publish this node's sealed objects to
        the rebuilt object directory.  A connection dropping again
        mid-handshake just re-enters the reconnect loop."""
        old, self.gcs = self.gcs, new_gcs
        if self._im is not None:
            new_gcs.rpc_observer = self._observe_gcs_rpc
        try:
            old.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.gcs.subscribe_remote(node_id=self.node_id)
        except (ConnectionError, TimeoutError, OSError):
            self._on_gcs_lost()
            return
        # Ask BEFORE re-registering whether this incarnation was declared
        # dead while we were away (the fence record survives GCS restarts
        # even though membership does not): a fenced zombie must kill its
        # stale workers first — re-registering and re-asserting its actors
        # straight away could double-execute against the replacements the
        # cluster started during the outage.
        hb = self._gcs_safe(self.gcs.heartbeat, self.node_id,
                            self.resources_available,
                            incarnation=self.incarnation)
        if hb == "fenced":
            self._on_fenced()  # kills workers, re-registers fresh,
            return             # re-publishes surviving store objects
        snapshot = self._gcs_safe(
            self.gcs.register_node,
            self.node_id, (self.node_ip, self.tcp_port),
            self.resources_total, store_path=self.store_path,
            hostname=socket.gethostname(),
            labels=self.node_labels, data_port=self.data_port,
            incarnation=self.incarnation)
        if snapshot is not None:
            self._apply_registration(snapshot)
        for oid, st in self._objects.items():
            if st.status == "store":
                self._gcs_safe(self.gcs.add_object_location,
                               oid.hex(), self.node_id, size=st.size or 0,
                               incarnation=self.incarnation)
        # Reconcile actor state: the restarted GCS loaded persisted actors
        # as "restarting" (it cannot know which survived); every actor
        # LIVE on this node re-asserts itself.
        for aid, actor in self._actors.items():
            if actor.state == "alive" and actor.conn is not None:
                self._gcs_safe(self.gcs.update_actor, aid.binary(), "alive",
                               node_id=self.node_id)
        sys.stderr.write(
            f"[ray_tpu] node {self.node_id[:8]}: reconnected to GCS\n")

    def _on_gcs_event(self, event: str, data):
        if event == "node_added":
            nid = data["node_id"]
            if nid != self.node_id:
                self._cluster_nodes[nid] = data
                inc = data.get("incarnation")
                if inc is not None and self._fenced.get(nid, -1) < inc:
                    # the node came back under a fresh incarnation: the
                    # fence applies to the OLD generation only
                    self._fenced.pop(nid, None)
            self._schedule()
        elif event == "node_dead":
            nid = data["node_id"]
            inc = data.get("incarnation")
            if inc is not None:
                prev = self._fenced.get(nid)
                if prev is None or inc > prev:
                    self._fenced[nid] = inc
            if nid == self.node_id:
                # Our own death declaration (drain completion, or a fence
                # we will learn about via the next rejected heartbeat) —
                # not a peer to clean up after.
                return
            self._on_node_death(nid, data.get("reason", ""))
        elif event == "node_suspect":
            nid = data["node_id"]
            suspect = bool(data.get("suspect"))
            info = self._cluster_nodes.get(nid)
            if info is not None:
                info["suspect"] = suspect
            if self._pull_manager is not None:
                # striped pulls rotate away from suspect holders (and
                # rotate back on recovery) — routing, not recovery:
                # reconstruction/replication repair fire only on DEAD
                self._pull_manager.on_node_suspect(nid, suspect)
            if suspect:
                # direct channels to the suspect node fall back to the
                # relayed path now (a false alarm costs latency, not
                # correctness — the raylet path dedups/fences)
                self._broadcast_direct_fence(node_id=nid)
            if not suspect:
                self._schedule()  # recovered: it can take work again
        elif event == "node_probe":
            self._relay_probe(data)
        elif event == "node_query":
            # targeted introspection (live stack dumps, log listings):
            # collect locally and answer with a one-way report post
            self._handle_node_query(data)
        elif event == "node_drain":
            nid = data.get("node_id")
            if nid == self.node_id:
                self._on_drain_request(float(data.get("timeout_s") or
                                             config.drain_timeout_s))
            else:
                # A peer is leaving: stop treating it as a replication /
                # locality-forwarding target while its objects migrate off.
                info = self._cluster_nodes.get(nid)
                if info is not None:
                    info["draining"] = True
        elif event == "object_at":
            oid = ObjectID.from_hex(data["oid"])
            st = self._objects.get(oid)
            if st is not None and st.status == "pending":
                st.status = "remote"
                st.locations = [data["node_id"]]
                st.size = max(st.size, data.get("size", 0))
                st.remote_inline = bool(data.get("inline", False))
                self._object_ready(oid)
            if oid in self._object_waiters or oid in self._dep_index:
                self._maybe_pull(oid)
        elif event == "pg_reserve":
            # GCS assigned this node a fragment of a cluster PG: register
            # it pending; _activate_pending_pgs (first thing every
            # schedule pass) reserves it and posts pg_fragment_ready.
            existing = self._pgs.get(data["pg_id"])
            if existing is not None and existing.fragment:
                # node-death repair can extend our fragment
                for i, b in data["bundles"].items():
                    if i not in existing.bundles:
                        existing.bundles[i] = b
                        existing.available[i] = dict(b)
                        existing.unreserved.add(i)
                        existing.state = "pending"  # reserve the new piece
            else:
                self._pgs[data["pg_id"]] = _PlacementGroup(
                    data["pg_id"], data["bundles"], "FRAGMENT",
                    fragment=True)
            self._schedule()
        elif event == "pg_ready":
            oid = self._cluster_pg_ready.pop(data["pg_id"], None)
            if oid is not None:
                self._object_inline(oid, _PG_READY_BLOB)
        elif event == "pg_remove":
            oid = self._cluster_pg_ready.pop(data["pg_id"], None)
            if oid is not None and self._object_status(oid) == "pending":
                self._object_error(oid, ValueError(
                    f"placement group {data['pg_id']} was removed before "
                    "its bundles could be reserved"))
            self.remove_pg(data["pg_id"], _from_gcs=True)

    def _on_node_death(self, node_id: str, reason: str):
        self._cluster_nodes.pop(node_id, None)
        # direct channels to workers on the dead node: tear down now (a
        # partitioned callee never produces a socket EOF)
        self._broadcast_direct_fence(node_id=node_id)
        if self._pull_manager is not None:
            # data-plane pulls sourced from the dead node rotate to other
            # holders (or fail back into _on_pull_failed for a re-lookup)
            self._pull_manager.on_node_dead(node_id)
        peer = self._peers.pop(node_id, None)
        if peer is not None:
            try:
                self._sel.unregister(peer.sock)
            except (KeyError, ValueError):
                pass
            try:
                peer.sock.close()
            except OSError:
                pass
        # In-flight pulls from the dead node: retry elsewhere.
        for oid, pull in list(self._pulls.items()):
            if pull["node"] == node_id:
                self._pull_by_rid.pop(pull["rid"], None)
                del self._pulls[oid]
                st = self._objects.get(oid)
                if st is not None and node_id in st.locations:
                    st.locations.remove(node_id)
                self._maybe_pull(oid, force_lookup=True)
        # Forwarded tasks: retry like a worker crash (actor tasks fail — the
        # actor itself restarts below and interrupted calls error).  Runs
        # BEFORE the lost-object scan so objects those retries will
        # re-produce register as in-flight and aren't double-submitted by
        # dependency reconstruction.
        for tid, (spec, nid) in list(self._forwarded.items()):
            if nid != node_id:
                continue
            del self._forwarded[tid]
            if spec.kind == ACTOR_CREATION_TASK:
                continue  # handled via the actor scan below
            if spec.kind == ACTOR_TASK:
                err = ActorDiedError(
                    spec.actor_id.hex() if spec.actor_id else "?",
                    f"node {node_id} died")
                for oid in spec.return_ids():
                    self._object_error(oid, err)
                self._record_event(spec, "FAILED", node_died=True)
            elif spec.retries_left > 0:
                spec.retries_left -= 1
                self._record_event(spec, "RETRYING", node_died=True)
                self._enqueue_ready(spec)
            else:
                err = WorkerCrashedError(
                    f"node {node_id} died while running {spec.name}")
                for oid in spec.return_ids():
                    self._object_error(oid, err)
                self._record_event(spec, "FAILED", node_died=True)
        # Remote objects whose only copy died with the node: lineage
        # reconstruction re-runs the creating task (reference:
        # ObjectRecoveryManager on node failure, object_recovery_manager.cc)
        # — ObjectLostError only when lineage is absent or the
        # reconstruction budget is exhausted.  Waiters blocked in get()
        # and dep-gated tasks stay registered: the object drops back to
        # "pending" and resolves when the re-run seals it.
        lost: List[ObjectID] = []
        for oid, st in list(self._objects.items()):
            if st.status != "remote":
                continue
            if node_id in st.locations:
                st.locations.remove(node_id)
            if not st.locations:
                lost.append(oid)
        # Eager availability: consult the directory for surviving copies
        # (replicas, or holders this raylet never heard of) BEFORE
        # falling into recompute — the GCS pruned the dead node
        # synchronously ahead of the node_dead push, so a hit here is a
        # live copy and recovery is a pull, not a re-run.  ONE batched
        # query: a dead node can take thousands of sole copies with it,
        # and per-object RPCs would serialize this thread on GCS latency.
        locs = None
        if lost:
            res = self._gcs_err_ok(self.gcs.get_object_locations_batch,
                                   [o.hex() for o in lost])
            if res is not _GCS_ERR:
                locs = res or {}
        for oid in lost:
            st = self._objects.get(oid)
            if st is None or st.status != "remote" or st.locations:
                continue  # a sibling's reconstruction already reset it
            loc = locs.get(oid.hex()) if locs is not None else None
            if loc:
                nodes = [n for n in loc["nodes"]
                         if n != self.node_id and n != node_id
                         and n in self._cluster_nodes]
                if nodes:
                    st.locations = nodes
                    st.size = max(st.size, loc.get("size", 0))
                    self._m_repl_recoveries += 1
                    if (oid in self._object_waiters
                            or oid in self._dep_index):
                        self._maybe_pull(oid)
                    continue
            if self.reconstruct_object(oid):
                continue
            self._object_error(oid, self._lost_error(
                oid, st, f"was on node {node_id} which died"))
        # Re-replication: local managed copies whose peer holder died —
        # restore the target copy count so the NEXT death is still a pull.
        repair: List[Tuple[ObjectID, "_ObjectState"]] = []
        for oid, st in list(self._objects.items()):
            if st.status != "store" or not st.replicated:
                continue
            if (node_id not in (st.replicas or ())
                    and node_id not in st.locations):
                continue
            if st.replicas and node_id in st.replicas:
                st.replicas.remove(node_id)
            if node_id in st.locations:
                st.locations.remove(node_id)
            repair.append((oid, st))
        if repair:
            res = self._gcs_err_ok(self.gcs.get_object_locations_batch,
                                   [o.hex() for o, _ in repair])
            if res is not _GCS_ERR:  # transient GCS trouble: best-effort
                for oid, st in repair:
                    loc = (res or {}).get(oid.hex()) or {}
                    if self._repair_replication(oid, st, loc,
                                                dead=node_id):
                        self._m_repl_repairs += 1
        # Actors executing on the dead node: restart per budget.
        for actor in list(self._actors.values()):
            if actor.node_id == node_id and actor.state != "dead":
                actor.node_id = None
                self._on_actor_death(actor.actor_id,
                                     f"node {node_id} died ({reason})")
        self._schedule()

    def _drop_peer(self, peer: _PeerConn):
        """Socket-level failure on a peer conn: close it; real node death is
        decided by the GCS health monitor, not by one broken socket."""
        try:
            self._sel.unregister(peer.sock)
        except (KeyError, ValueError):
            pass
        try:
            peer.sock.close()
        except OSError:
            pass
        if self._peers.get(peer.node_id) is peer:
            del self._peers[peer.node_id]

    def _get_peer(self, node_id: str) -> Optional[_PeerConn]:
        peer = self._peers.get(node_id)
        if peer is not None:
            return peer
        info = self._cluster_nodes.get(node_id)
        if info is None or not info.get("address"):
            try:
                info = self.gcs.get_node(node_id)
            except (ConnectionError, TimeoutError, OSError):
                info = None
            if info is None or not info.get("alive") or not info.get("address"):
                return None
            self._cluster_nodes[node_id] = info
        try:
            sock = socket.create_connection(tuple(info["address"]), timeout=5)
        except OSError:
            return None
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setblocking(True)
        peer = _PeerConn(sock, node_id)
        self._peers[node_id] = peer
        self._sel.register(sock, selectors.EVENT_READ, ("peer", peer))
        peer.send({"t": "peer_hello", "node_id": self.node_id,
                   "incarnation": self.incarnation})
        return peer

    def _on_peer_readable(self, peer: _PeerConn):
        try:
            data = peer.sock.recv(1 << 20)
        except OSError:
            data = b""
        if not data:
            self._drop_peer(peer)
            return
        self._m_trains += 1
        self._m_train_bytes += len(data)
        if self._im is not None:
            self._im["train_bytes"].observe(len(data))
        peer.rbuf += data
        self._begin_drain()
        try:
            self._drain_frames(
                peer.rbuf,
                lambda msg: self._handle_peer_msg(peer, msg),
                lambda: self._peer_alive(peer))
        finally:
            self._end_drain()

    def _handle_peer_msg(self, peer: _PeerConn, msg: dict):
        self._m_frames += 1
        t = msg["t"]
        if t == "xtask":
            self._handle_xtask(peer, msg)
        elif t == "xdone":
            self._handle_xdone(msg)
        elif t == "xstream_item":
            self._handle_xstream_item(msg)
        elif t == "xactor_death":
            self._handle_xactor_death(msg)
        elif t == "xdirect_done":
            self._handle_xdirect_done(msg)
        elif t == "xkill":
            self.kill_actor(msg["actor_id"], msg.get("no_restart", True))
        elif t == "xcancel":
            # one-hop cancel relay for forwarded/foreign-executed tasks
            # (_relay=False: the origin already broadcast — no loops)
            self._cancel_tid(msg["task_id"],
                             deadline=msg.get("deadline", False),
                             recursive=msg.get("recursive", True),
                             _relay=False)
        elif t == "pull":
            self._handle_pull(peer, msg)
        elif t == "pull_meta":
            self._handle_pull_meta(msg)
        elif t == "chunk":
            self._handle_pull_chunk(msg)
        elif t == "pull_err":
            self._handle_pull_err(msg)
        elif t == "xreplicate":
            self._handle_xreplicate(msg)
        elif t == "xreplica_drop":
            self._handle_xreplica_drop(msg)
        elif t == "xcheckpoint":
            self._handle_xcheckpoint(msg)

    # ---- task forwarding (spillback / actor routing) ----

    def _forward_task(self, spec: TaskSpec, node_id: str) -> bool:
        peer = self._get_peer(node_id)
        if peer is None:
            return False
        inline_deps: Dict[str, bytes] = {}
        store_deps: Dict[str, str] = {}
        for oid in spec.dependency_ids():
            st = self._objects.get(oid)
            if st is None:
                continue
            if st.status == "inline":
                inline_deps[oid.hex()] = st.value
            elif st.status == "store":
                store_deps[oid.hex()] = (self.node_id, st.size)
            elif st.status == "remote" and st.locations:
                # ship EVERY known holder (multi-source striping seeds) +
                # size for locality/admission math + the inline flag (an
                # inline remote object must pull over the control plane —
                # the holder's STORE can't serve it)
                store_deps[oid.hex()] = (list(st.locations), st.size,
                                         st.remote_inline)
        fwd_t0 = time.time() if self._spec_traced(spec) else 0.0
        spec._acquired_pool = None
        spec._spill_count = getattr(spec, "_spill_count", 0) + 1
        self._forwarded[spec.task_id] = (spec, node_id)
        if spec.kind == ACTOR_CREATION_TASK:
            actor = self._actors.get(spec.actor_id)
            if actor is not None:
                actor.node_id = node_id  # tentative; confirmed by xdone
        self._record_event(spec, "SPILLED", to_node=node_id)
        try:
            peer.send({"t": "xtask", "spec": spec,
                       "inline_deps": inline_deps,
                       "store_deps": store_deps, "origin": self.node_id})
        except OSError:
            del self._forwarded[spec.task_id]
            if spec.kind == ACTOR_CREATION_TASK:
                actor = self._actors.get(spec.actor_id)
                if actor is not None and actor.node_id == node_id:
                    actor.node_id = None  # roll back the tentative placement
            self._drop_peer(peer)
            return False
        if fwd_t0:
            # forward hop: dep snapshotting + the xtask frame hand-off;
            # the receiving raylet opens its own inbox span on receipt
            self._trace_hop(spec, "raylet.forward", fwd_t0, to_node=node_id)
        return True

    def _handle_xtask(self, peer: _PeerConn, msg: dict):
        spec: TaskSpec = msg["spec"]
        origin: str = msg["origin"]
        for h, blob in (msg.get("inline_deps") or {}).items():
            oid = ObjectID.from_hex(h)
            if self._object_status(oid) not in ("inline", "store"):
                self._object_inline(oid, blob)
        for h, dep in (msg.get("store_deps") or {}).items():
            node, size = dep[0], dep[1]
            oid = ObjectID.from_hex(h)
            st = self._obj(oid)
            if st.status == "pending":
                st.status = "remote"
                st.locations = list(node) if isinstance(node, list) else [node]
                st.size = max(st.size, size or 0)
                if len(dep) > 2:
                    st.remote_inline = bool(dep[2])
        # Route the results back the moment every return resolves — this
        # catches every completion path (inline/store/error) with the same
        # machinery local get() uses.
        self.async_get(
            spec.return_ids(),
            lambda results, s=spec, o=origin: self._xdone_cb(o, s, results))
        if spec.num_returns == STREAMING_RETURNS:
            self._foreign_streams[spec.task_id] = origin
        self.submit_task(spec, foreign_origin=origin)

    def _xdone_cb(self, origin: str, spec: TaskSpec, results: Dict[str, tuple]):
        peer = self._get_peer(origin)
        if peer is None:
            return  # origin node is gone; results stay locally
        out = {}
        contains = {}
        for h, r in results.items():
            if r[0] == "store":
                st_out = self._objects.get(ObjectID.from_hex(h))
                out[h] = ("store", self.node_id,
                          st_out.size if st_out is not None else 0)
            else:
                out[h] = r
            st = self._objects.get(ObjectID.from_hex(h))
            if st is not None and st.contains:
                contains[h] = st.contains  # owner re-pins the inner refs
        xdone = {"t": "xdone", "task_id": spec.task_id, "results": out,
                 "contains": contains}
        if spec.kind == ACTOR_CREATION_TASK:
            # piggyback the hosted worker's direct-call listener so the
            # OWNER can broker caller→worker channels across nodes
            local = self._actors.get(spec.actor_id)
            if (local is not None and local.conn is not None
                    and local.conn.direct_addr):
                xdone["direct_info"] = {
                    "addr": local.conn.direct_addr,
                    "incarnation": self.incarnation,
                    "node_id": self.node_id,
                    "pid": local.conn.pid,
                }
        try:
            peer.send(xdone)
        except OSError:
            self._drop_peer(peer)

    def _handle_xdone(self, msg: dict):
        entry = self._forwarded.pop(msg["task_id"], None)
        spec = entry[0] if entry else None
        xdone_t0 = (time.time()
                    if spec is not None and self._spec_traced(spec) else 0.0)
        failed = False
        contains = msg.get("contains", {})
        for h, r in msg["results"].items():
            oid = ObjectID.from_hex(h)
            if r[0] == "inline":
                self._object_inline(oid, r[1], contains=contains.get(h))
            elif r[0] == "error":
                failed = True
                self._object_error(oid, r[1])
            else:  # ("store", node_id, size)
                st = self._obj(oid)
                self._set_contains(st, contains.get(h))
                if st.status in ("pending", "remote"):
                    st.status = "remote"
                    if r[1] not in st.locations:
                        st.locations.append(r[1])
                    if len(r) > 2:
                        st.size = max(st.size, r[2] or 0)
                    self._object_ready(oid)
        if spec is None:
            return
        self._record_event(spec, "FAILED" if failed else "FINISHED",
                           remote=True)
        if xdone_t0:
            # owner-side result registration for a forwarded task (the
            # executing node's raylet.result covered the seal over there)
            self._trace_hop(spec, "raylet.xdone", xdone_t0,
                            status="ERROR" if failed else "OK")
        if spec.kind == ACTOR_CREATION_TASK:
            actor = self._actors.get(spec.actor_id)
            if actor is not None:
                if failed:
                    actor.node_id = None
                    self._on_actor_death(spec.actor_id,
                                         "creation task failed",
                                         allow_restart=False)
                else:
                    actor.state = "alive"
                    actor.node_id = entry[1]
                    actor.direct_info = msg.get("direct_info")
                    if self.cluster_mode:
                        self._gcs_post("update_actor",
                                       spec.actor_id.binary(), "alive",
                                       node_id=entry[1])
                    self._pump_actor(actor)

    def _handle_xactor_death(self, msg: dict):
        actor = self._actors.get(msg["actor_id"])
        if actor is None or actor.state == "dead":
            return
        actor.node_id = None
        self._on_actor_death(msg["actor_id"], msg.get("reason", "died"))

    def _gcs_safe(self, fn, *args, **kw):
        try:
            return fn(*args, **kw)
        except (ConnectionError, TimeoutError, OSError):
            return None

    def _gcs_err_ok(self, fn, *args, **kw):
        """Like _gcs_safe but distinguishes an RPC failure (_GCS_ERR) from
        an authoritative None — callers must not treat a timeout as
        'does not exist'."""
        try:
            return fn(*args, **kw)
        except (ConnectionError, TimeoutError, OSError):
            return _GCS_ERR

    def _gcs_post(self, op: str, *args, **kw):
        """One-way GCS update (no reply wait) — keeps the event thread off
        GCS round-trips on per-object hot paths."""
        try:
            if isinstance(self.gcs, GcsClient):
                self.gcs.post(op, *args, **kw)
            else:
                getattr(self.gcs, op)(*args, **kw)
        except (ConnectionError, TimeoutError, OSError):
            pass

    # ---- chunked object pulls (reference: pull_manager.h:52) ----

    def _raylet_store(self):
        # Also called from data-plane server/receiver threads: guard the
        # lazy attach so two threads never race two attachments.
        # Double-checked locking: the unlocked probe only ever skips the
        # attach when another thread already completed it (reference
        # assignment is atomic under the GIL).
        if self._store is None and self.store_path:  # unguarded-ok: DCL probe
            from ray_tpu.core.object_store import ShmObjectStore

            with self._store_lock:
                if self._store is None:
                    self._store = ShmObjectStore(self.store_path)
        return self._store  # unguarded-ok: atomic reference read

    def _peer_data_addr(self, node_id: str):
        """(host, data_port) of a peer's data-plane listener, or None when
        unknown / the peer runs without a data channel.  Called from the
        pull manager's DIALER thread (GcsClient calls are thread-safe;
        _cluster_nodes updates are GIL-atomic dict ops); a channel-less
        answer is tombstoned by the pull manager so it isn't re-queried
        per pull."""
        info = self._cluster_nodes.get(node_id)
        if info is None or not info.get("data_port"):
            info = self._gcs_safe(self.gcs.get_node, node_id)
            if info is None or not info.get("alive"):
                return None
            self._cluster_nodes[node_id] = info
        addr, port = info.get("address"), info.get("data_port")
        if not addr or not port:
            return None
        return (addr[0], port)

    # ---- bounded sender pool (python-fallback pull serving) ----

    def _pull_sender_submit(self, fn):
        """Queue a chunk-stream job onto the bounded sender pool (replaces
        the old unbounded thread-per-request spawn).  Blocking sendalls
        must stay off the event thread — two raylets pulling large objects
        from each other would deadlock on full TCP buffers."""
        if self._pull_send_q is None:
            self._pull_send_q = _queue.SimpleQueue()
        cap = max(1, config.pull_sender_threads)
        if self._pull_send_q.qsize() >= cap and self._pull_sender_count >= cap:
            self._m_pull_sender_saturated += 1
        self._pull_send_q.put(fn)
        if self._pull_sender_count < cap:
            self._pull_sender_count += 1
            threading.Thread(target=self._pull_sender_loop,
                             name=f"pull-send-{self._pull_sender_count}",
                             daemon=True).start()

    def _pull_sender_loop(self):
        q = self._pull_send_q
        while not self._shutdown:
            try:
                fn = q.get(timeout=5.0)
            except _queue.Empty:
                continue
            self._safe(fn)

    def _handle_pull(self, peer: _PeerConn, msg: dict):
        """Serve an object to a peer: inline blob in one frame, store bytes
        as a pull_meta + chunk stream.

        This is the python-fallback data path (inline objects, peers
        without a data channel, RAY_TPU_DATA_CHANNEL=0); bulk store bytes
        normally move over data_channel.py.  The chunk stream runs on the
        BOUNDED SENDER POOL: a blocking sendall on the event thread would
        stop this raylet from reading its own sockets — two raylets
        pulling large objects from each other would deadlock on full TCP
        buffers.  The store read is thread-safe (pin via get_buffer /
        release when done); _objects is only touched here on the event
        thread.
        """
        rid = msg["rid"]
        oid = ObjectID.from_hex(msg["id"])
        st = self._objects.get(oid)
        inline_value = st.value if (st is not None and st.status == "inline") \
            else None
        store = self._raylet_store()

        def stream():
            try:
                if inline_value is not None:
                    peer.send({"t": "pull_meta", "rid": rid, "kind": "inline",
                               "size": len(inline_value)})
                    peer.send({"t": "chunk", "rid": rid, "data": inline_value,
                               "eof": True})
                    return
                buf = store.get_buffer(oid) if store is not None else None
                if buf is None and store is not None \
                        and store.has_spilled(oid):
                    # stream the spilled file from disk, chunk by chunk —
                    # never materialize (possibly store-sized+) bytes
                    try:
                        f = open(store._spill_path(oid), "rb")
                    except OSError:
                        peer.send({"t": "pull_err", "rid": rid,
                                   "error": f"object {oid.hex()} freed"})
                        return
                    with f:
                        size = os.fstat(f.fileno()).st_size
                        peer.send({"t": "pull_meta", "rid": rid,
                                   "kind": "store", "size": size})
                        chunk = config.object_transfer_chunk_bytes
                        sent = 0
                        while True:
                            data = f.read(chunk)
                            sent += len(data)
                            eof = sent >= size or not data
                            peer.send({"t": "chunk", "rid": rid,
                                       "data": data, "eof": eof})
                            if eof:
                                break
                    return
                if buf is None:
                    peer.send({"t": "pull_err", "rid": rid,
                               "error": f"object {oid.hex()} not here"})
                    return
                try:
                    size = len(buf)
                    peer.send({"t": "pull_meta", "rid": rid, "kind": "store",
                               "size": size})
                    chunk = config.object_transfer_chunk_bytes
                    for off in range(0, size, chunk):
                        peer.send({"t": "chunk", "rid": rid,
                                   "data": bytes(buf[off:off + chunk]),
                                   "eof": off + chunk >= size})
                    if size == 0:
                        peer.send({"t": "chunk", "rid": rid, "data": b"",
                                   "eof": True})
                finally:
                    del buf
                    store.release(oid)
            except OSError:
                self.call_async(self._drop_peer, peer)

        self._pull_sender_submit(stream)

    def _maybe_pull(self, oid: ObjectID, force_lookup: bool = False,
                    priority: int = 1, trace_ctx: Optional[dict] = None):
        """Start fetching a non-local object.  Location from local metadata,
        else the GCS directory (registering a watch when unknown).

        ``priority``: 0 = task-argument pull (admitted ahead of
        speculative/get prefetch, which is 1) — only meaningful on the
        pull-manager path.

        ``trace_ctx``: span context of the request whose arguments need
        this object — the pull becomes a child span in its waterfall
        (one per data-channel pull, emitted when the pull concludes).

        Store objects normally move over the zero-copy data plane
        (pull_manager striping across every known holder); inline objects
        and peers without a data channel fall back to the single-source
        pickled-chunk path below."""
        if not self.cluster_mode:
            return
        st = self._obj(oid)
        if st.status not in ("pending", "remote") or oid in self._pulls:
            return
        if (trace_ctx is not None and trace_ctx.get("sampled", True)
                and _tracing.tracing_enabled()
                and oid not in self._pull_trace):
            if len(self._pull_trace) > 2048:  # never-concluding watches
                self._pull_trace.pop(next(iter(self._pull_trace)))
            self._pull_trace[oid] = (time.time(), trace_ctx)
        if (self._pull_manager is not None and not force_lookup
                and self._pull_manager.active(oid)):
            # already pulling: request() below would only dedup — but let a
            # task-arg call bump a queued prefetch's admission priority
            if priority == 0:
                self._pull_manager.request(oid, st.size, list(st.locations),
                                           priority=0)
            return
        if st.status == "pending" or force_lookup or not st.locations:
            loc = self._gcs_safe(self.gcs.get_object_locations, oid.hex(),
                                 watcher=self.node_id)
            if not loc or not loc["nodes"]:
                return  # watch registered; object_at retriggers us
            st.locations = [n for n in loc["nodes"] if n != self.node_id]
            if not st.locations:
                return
            st.size = max(st.size, loc.get("size", 0))
            st.remote_inline = bool(loc.get("inline", False))
            if st.status == "pending":
                st.status = "remote"
        if (self._pull_manager is not None and config.data_channel
                and not st.remote_inline):
            if self._pull_manager.request(oid, st.size, list(st.locations),
                                          priority=priority):
                return
            # no holder reachable on the data plane: fall through to the
            # control-plane path (peer may predate the data channel)
        # Randomize the holder so N concurrent pullers don't all hammer
        # locations[0] (the multi-source data plane stripes instead; this
        # is the single-channel fallback).
        target = random.choice(st.locations)
        peer = self._get_peer(target)
        if peer is None:
            # Unreachable holder: drop it from the directory too (else a
            # force_lookup keeps returning the same node until the GCS
            # health timeout) and retry on a timer rather than recursing.
            st.locations.remove(target)
            self._gcs_post("remove_object_location", oid.hex(), target)
            if st.locations:
                self._maybe_pull(oid)
            else:
                st.status = "pending"
                self._recover_or_retry(oid, st)
            return
        rid = next(self._pull_rid)
        self._pulls[oid] = {"rid": rid, "node": target, "kind": None,
                            "buf": None, "mv": None, "off": 0, "oid": oid}
        self._pull_by_rid[rid] = oid
        try:
            peer.send({"t": "pull", "rid": rid, "id": oid.hex()})
        except OSError:
            self._pull_by_rid.pop(rid, None)
            self._pulls.pop(oid, None)
            self._drop_peer(peer)

    def _handle_pull_meta(self, msg: dict):
        oid = self._pull_by_rid.get(msg["rid"])
        if oid is None:
            return
        pull = self._pulls[oid]
        pull["kind"] = msg["kind"]
        pull["size"] = msg["size"]
        st_meta = self._objects.get(oid)
        if st_meta is not None:
            st_meta.size = max(st_meta.size, msg["size"])
        if msg["kind"] == "store" and msg["size"] > 0:
            store = self._raylet_store()
            try:
                # spill mode: never evict sealed data to admit a pull;
                # overflow lands in the spill dir at eof instead
                pull["mv"] = store.create(
                    oid, msg["size"],
                    allow_evict=not config.object_store_spill)
            except FileExistsError:
                pass  # already local (raced another pull path)
            except Exception:  # noqa: BLE001  (store full etc.)
                pull["mv"] = None
        if pull["kind"] == "inline" or pull["mv"] is None:
            pull["buf"] = bytearray()

    def _handle_pull_chunk(self, msg: dict):
        oid = self._pull_by_rid.get(msg["rid"])
        if oid is None:
            return
        pull = self._pulls[oid]
        data = msg["data"]
        if pull.get("mv") is not None:
            mv = pull["mv"]
            mv[pull["off"]:pull["off"] + len(data)] = data
            pull["off"] += len(data)
        elif pull.get("buf") is not None:
            pull["buf"] += data
        if not msg.get("eof"):
            return
        # complete
        self._pull_by_rid.pop(msg["rid"], None)
        del self._pulls[oid]
        self._finish_pull_trace(oid, "control_plane")
        st = self._obj(oid)
        if pull["kind"] == "inline":
            self._object_inline(oid, bytes(pull["buf"]))
            return
        store = self._raylet_store()
        if pull.get("mv") is not None:
            del pull["mv"]
            store.seal(oid)
            store.release(oid)
        elif store is not None:
            try:
                mv = store.create(
                    oid, len(pull["buf"]),
                    allow_evict=not config.object_store_spill)
                mv[:] = pull["buf"]
                del mv
                store.seal(oid)
                store.release(oid)
            except FileExistsError:
                pass
            except Exception:  # noqa: BLE001
                if config.object_store_spill:
                    # no arena room: the pulled bytes overflow to disk
                    store.spill_raw(oid, pull["buf"])
                else:
                    self._object_error(oid, ObjectLostError(
                        f"no store capacity for pulled object {oid.hex()}"))
                    return
        self._object_in_store(oid)

    def _handle_pull_err(self, msg: dict):
        oid = self._pull_by_rid.pop(msg["rid"], None)
        if oid is None:
            return
        self._finish_pull_trace(oid, "control_plane", status="ERROR",
                                error=str(msg.get("error", "pull failed")))
        pull = self._pulls.pop(oid, None)
        st = self._objects.get(oid)
        if st is not None and pull is not None:
            if pull["node"] in st.locations:
                st.locations.remove(pull["node"])
            self._gcs_post("remove_object_location", oid.hex(),
                           pull["node"])
            if st.status == "remote":
                if st.locations:
                    self._maybe_pull(oid)
                else:
                    st.status = "pending"
                    self._recover_or_retry(oid, st)

    def _finish_pull_trace(self, oid: ObjectID, path: str,
                           status: str = "OK", error: Optional[str] = None):
        """Close out a traced argument pull: one ``pull.fetch`` child span
        under the requesting task, with the transfer path (data_channel /
        control fallback) and byte count from the directory metadata."""
        rec = self._pull_trace.pop(oid, None)
        if rec is None:
            return
        t0, ctx = rec
        st = self._objects.get(oid)
        _tracing.hop(f"pull.fetch {oid.hex()[:8]}", ctx, t0, time.time(),
                     status=status, error=error, proc="raylet",
                     oid=oid.hex(), path=path,
                     bytes=(st.size if st is not None else 0) or 0)
        self._arm_trace_flush()

    # ---- data-plane pull callbacks (posted by the pull manager) ----

    def _on_pull_done(self, oid: ObjectID):
        """A data-plane pull sealed the object in the local store."""
        self._finish_pull_trace(oid, "data_channel")
        st = self._obj(oid)
        if st.status in ("pending", "remote"):
            self._object_in_store(oid)

    def _on_pull_failed(self, oid: ObjectID, bad_nodes: List[str]):
        """Every data-plane source failed: scrub the dead holders from the
        directory and re-resolve with backoff (mirrors _handle_pull_err);
        the retry may pick fresh holders, fall back to the control-plane
        path when no data channel can be dialed — or, when no holder
        exists anywhere anymore, reconstruct from lineage."""
        self._finish_pull_trace(oid, "data_channel", status="ERROR",
                                error=f"all sources failed: {bad_nodes}")
        st = self._objects.get(oid)
        if st is None or st.status not in ("pending", "remote"):
            return
        for node in bad_nodes:
            if node in st.locations:
                st.locations.remove(node)
            self._gcs_post("remove_object_location", oid.hex(), node)
        if oid not in self._object_waiters and oid not in self._dep_index:
            # nobody is waiting anymore; an abandoned replication pull
            # must drop its marker too (best-effort, no retry)
            self._replicating.discard(oid)
            return
        if st.locations:
            self._maybe_pull(oid)
            return
        st.status = "pending"
        self._recover_or_retry(oid, st)

    def _recover_or_retry(self, oid: ObjectID, st: "_ObjectState"):
        """A previously sealed object has no reachable holder left.  Order
        of recovery: (1) re-resolve the directory — another live node may
        hold a copy this raylet hasn't heard of; (2) reconstruct from
        lineage; (3) no lineage (ray.put / actor result): retry the
        lookup with backoff — a holder may still re-register (e.g. after
        a GCS restart).  When lineage exists but reconstruction is
        impossible (budget exhausted, unrecoverable dependency), the
        object errors NOW so waiters raise ObjectLostError instead of
        hanging on a directory watch that can never fire."""
        loc = self._gcs_err_ok(self.gcs.get_object_locations, oid.hex(),
                               watcher=self.node_id)
        if loc is not _GCS_ERR:
            nodes = [n for n in (loc or {}).get("nodes", ())
                     if n != self.node_id and n in self._cluster_nodes]
            if nodes:
                # Retry via a backoff timer, not inline: the directory may
                # still list a dying node the health monitor hasn't pruned
                # yet, and an inline _maybe_pull would mutually recurse
                # through this path until it is.
                st.locations = nodes
                st.status = "remote"
                st.lookup_attempts += 1
                self.add_timer(
                    self._retry_policy.delay(st.lookup_attempts - 1),
                    lambda: self._maybe_pull(oid))
                return
            if st.creating_spec is not None:
                if not self.reconstruct_object(oid):
                    self._object_error(oid, self._lost_error(
                        oid, st, "has no reachable copy left"))
                return
        # GCS unreachable, or reachable but no lineage: backoff retry
        st.lookup_attempts += 1
        self.add_timer(self._retry_policy.delay(st.lookup_attempts - 1),
                       lambda: self._maybe_pull(oid, force_lookup=True))

    def _pull_tick(self):
        """Repeating watchdog: stalled-range rotation + admission retries
        for the pull manager (event thread)."""
        if self._pull_manager is not None:
            self._pull_manager.tick()
        if not self._shutdown:
            self.add_timer(1.0, self._pull_tick)

    def _remote_deps_pending(self, spec: TaskSpec) -> bool:
        """True when some dependency is not locally materialized — triggers
        the pulls; the task re-enters dispatch when they land.  ("pending"
        can appear here too when a holder node died after dep gating.)"""
        pending = False
        for oid in spec.dependency_ids():
            st = self._objects.get(oid)
            status = st.status if st is not None else "pending"
            if status not in ("inline", "store", "error"):
                self._maybe_pull(oid, priority=0,  # task arg: high priority
                                 trace_ctx=spec.trace_ctx)
                pending = True
        return pending

    # --------------------------------------------------------------- refcount

    def apply_ref_events(self, events: List[Tuple[str, ObjectID]],
                         conn: Optional[_WorkerConn] = None):
        """Ordered hold ("h") / release ("r") transitions from one process
        (reference: ReferenceCounter updates).  Free happens only after a
        grace period at zero — covers the window where a ref travels
        inside a serialized result before the receiver announces its
        hold (the full borrowing protocol's job).  ``conn``-attributed
        holds are force-released if the process dies without flushing."""
        for kind, oid in events:
            st = self._obj(oid)
            if kind == "h":
                st.holders += 1
                st.tracked = True
                if conn is not None:
                    conn.held[oid] = conn.held.get(oid, 0) + 1
            else:
                st.holders -= 1
                if conn is not None:
                    n = conn.held.get(oid, 0) - 1
                    if n <= 0:
                        conn.held.pop(oid, None)
                    else:
                        conn.held[oid] = n
                self._maybe_free(oid)

    def _release_conn_holds(self, conn: _WorkerConn):
        """A worker/driver process died: drop every hold it still had."""
        for oid, n in conn.held.items():
            st = self._objects.get(oid)
            if st is not None:
                st.holders -= n
                self._maybe_free(oid)
        conn.held.clear()

    def release_refs(self, oids: List[ObjectID]):
        self.apply_ref_events([("r", o) for o in oids])

    def drop_object(self, oid: ObjectID):
        """Explicit user free: remove the entry now, releasing any borrow
        pins its bytes held on inner refs."""
        st = self._objects.pop(oid, None)
        if st is not None:
            self._teardown_entry(oid, st)

    def _teardown_entry(self, oid: ObjectID, st: "_ObjectState"):
        """Shared final teardown for a removed object entry (explicit free
        and auto-free): lineage accounting, store bytes, borrow-pin
        release, location directory."""
        if st.creating_spec is not None:
            self._lineage_count -= 1
        if st.status == "store":
            store = self._raylet_store()
            if store is not None:
                try:
                    store.delete(oid)
                except Exception:  # noqa: BLE001
                    pass
        if st.contains:
            # this blob's inner refs lose their borrow pins; they free in
            # turn once nothing else holds them
            for inner in st.contains:
                inner_st = self._objects.get(inner)
                if inner_st is not None:
                    inner_st.pins -= 1
                    self._maybe_free(inner)
        if self.cluster_mode:
            self._gcs_post("remove_object_location", oid.hex(), self.node_id)
        if st.replicas:
            # the primary is gone for good: managed secondaries must not
            # outlive it (they hold no refs of their own)
            for node in st.replicas:
                peer = self._get_peer(node)
                if peer is None:
                    continue
                try:
                    peer.send({"t": "xreplica_drop", "id": oid.hex()})
                except OSError:
                    self._drop_peer(peer)

    def _maybe_free(self, oid: ObjectID):
        st = self._objects.get(oid)
        if (st is None or not st.tracked or st.holders > 0 or st.pins > 0
                or st.free_armed):
            return
        if st.status == "pending":
            # in-flight result: never drop the entry (and its lineage) out
            # from under the producing task — re-checked on resolution
            # (_object_ready calls _maybe_free)
            return
        if oid in self._dep_index or oid in self._object_waiters:
            return
        st.free_armed = True
        # Batched grace queue: a 10k-task fan-out frees 10k objects in a
        # burst — one timer per object is 10k heap pushes now and 10k
        # callback pops at grace expiry.  The grace period is a constant,
        # so deadlines are monotonic: a FIFO deque + ONE sweeper timer
        # gives the same semantics for O(1) per free.
        self._free_queue.append((time.monotonic() + config.ref_free_grace_s,
                                 oid))
        if not self._free_sweep_armed:
            self._free_sweep_armed = True
            self.add_timer(config.ref_free_grace_s, self._sweep_free_queue)

    def _sweep_free_queue(self):
        now = time.monotonic()
        q = self._free_queue
        while q and q[0][0] <= now:
            _, oid = q.popleft()
            self._safe(lambda o=oid: self._free_if_unreferenced(o))
        if q:
            self.add_timer(max(0.0, q[0][0] - now), self._sweep_free_queue)
        else:
            self._free_sweep_armed = False

    def _free_if_unreferenced(self, oid: ObjectID):
        st = self._objects.get(oid)
        if st is None:
            return
        st.free_armed = False
        if (st.holders > 0 or st.pins > 0 or st.status == "pending"
                or oid in self._dep_index or oid in self._object_waiters):
            return
        del self._objects[oid]
        self._teardown_entry(oid, st)

    def _pin_deps(self, spec: TaskSpec):
        """Pin dependency objects — declared top-level deps AND refs
        serialized inside inline arg values (spec.inner_refs, the borrow
        pins) — for the task's lifetime: released when every return
        resolves (the same all-paths completion signal the cluster xdone
        path uses).  The executor's own hold announcements are flushed
        ahead of its done message, so by release time any ref the task
        kept is already counted."""
        deps = list(spec.dependency_ids())
        if spec.inner_refs:
            deps += spec.inner_refs
        if not deps:
            return
        for oid in deps:
            self._obj(oid).pins += 1

        def unpin(_results, deps=deps):
            for oid in deps:
                st = self._objects.get(oid)
                if st is not None:
                    st.pins -= 1
                    self._maybe_free(oid)

        self.async_get(spec.return_ids(), unpin)

    def _lost_error(self, oid: ObjectID, st: Optional["_ObjectState"],
                    why: str) -> ObjectLostError:
        """ObjectLostError whose message says WHY recovery didn't run:
        missing lineage vs an exhausted reconstruction budget."""
        spec = st.creating_spec if st is not None else None
        if spec is None:
            detail = ("no lineage retained (ray.put / actor result, or "
                      "the lineage cap evicted it)")
        elif (st.recon_attempts >= config.max_object_reconstructions
                or spec.retries_left <= 0):
            detail = (f"reconstruction budget exhausted after "
                      f"{st.recon_attempts} reconstruction(s) "
                      f"(max_object_reconstructions="
                      f"{config.max_object_reconstructions}, "
                      f"retries_left={max(0, spec.retries_left)})")
        else:
            detail = ("a dependency could not be recovered (missing "
                      "lineage, errored, or reconstruction depth cap)")
        return ObjectLostError(f"object {oid.hex()} {why}; {detail}")

    def _task_in_flight(self, tid: TaskID) -> bool:
        """Is the task currently producing its returns (queued, dep-gated,
        forwarded, dispatched, or already reconstructing)?  Used to avoid
        double-submitting a creating task during recovery."""
        if (tid in self._reconstructing or tid in self._waiting
                or tid in self._forwarded):
            return True
        if any(s.task_id == tid for s in self._ready_queue):
            return True
        if any(tid in c.inflight for c in self._workers.values()):
            return True
        return any(tid in a.inflight
                   or any(s.task_id == tid for s in a.queue)
                   for a in self._actors.values())

    def _live_locations(self, st: "_ObjectState") -> List[str]:
        return [n for n in st.locations
                if n == self.node_id or n in self._cluster_nodes]

    def _dep_recoverable(self, dep: ObjectID, store, _depth: int) -> bool:
        """Ensure one dependency of a task being reconstructed is (or will
        become) materializable: live remote holders first, then the GCS
        directory, then recursive reconstruction — including deps whose
        only copy died with a node.  An unrecoverable dep is ERRORED here
        (not just reported False): its own waiters must raise rather than
        hang, and the node-death scan won't revisit it once its status
        left "remote"."""
        ds = self._objects.get(dep)
        status = ds.status if ds is not None else "pending"
        if status == "inline":
            return True
        if status == "error":
            return False  # re-running the parent can only re-fail
        if status == "store":
            if store is None or store.contains(dep):
                return True  # bytes are present locally
        elif status == "remote":
            if self._live_locations(ds):
                return True  # another live holder; dispatch-time pull
            # re-resolve across the cluster: the directory may know
            # holders this raylet hasn't heard of.  A transient GCS
            # failure is NOT "no holders" — leave the dep alone and let
            # the dispatch-time pull retry through the backoff paths.
            loc = self._gcs_err_ok(self.gcs.get_object_locations,
                                   dep.hex(), watcher=self.node_id)
            if loc is _GCS_ERR:
                return True
            nodes = [n for n in (loc or {}).get("nodes", ())
                     if n == self.node_id or n in self._cluster_nodes]
            if nodes:
                ds.locations = [n for n in nodes if n != self.node_id] \
                    or nodes
                return True
            ds.status = "pending"
            ds.locations = []
        elif status == "pending" and self._task_in_flight(dep.task_id()):
            return True  # producer in flight; dependency gating waits
        if self.reconstruct_object(dep, _depth + 1):
            return True
        self._object_error(dep, self._lost_error(
            dep, self._objects.get(dep), "has no reachable copy left"))
        return False

    def reconstruct_object(self, oid: ObjectID, _depth: int = 0) -> bool:
        """Lineage reconstruction (reference: ObjectRecoveryManager,
        `object_recovery_manager.h:41`): re-run the task that created an
        object whose bytes were evicted — or whose only copy died with a
        node — under the per-object reconstruction budget.  Missing
        dependencies re-resolve across the cluster (live holders first)
        or reconstruct recursively (bounded depth).  Returns False when
        lineage is absent or the budget is exhausted; the caller raises
        ObjectLostError."""
        st = self._objects.get(oid)
        spec = st.creating_spec if st is not None else None
        if (spec is None or spec.kind != NORMAL_TASK
                or _depth > config.max_reconstruction_depth):
            return False
        if spec.task_id in self._reconstructing:
            return True  # already re-running; the waiter resolves with it
        store = self._raylet_store()
        if (st.status == "store" and store is not None
                and store.contains(oid)):
            return True  # false alarm: bytes are present
        if st.status == "remote" and self._live_locations(st):
            return True  # a live holder remains: pull, don't re-run
        if self._task_in_flight(spec.task_id):
            # Creating task already re-queued/dispatched — e.g. the
            # forwarded-task retry loop re-enqueued it in this same
            # node-death pass (the return can still read "remote" with no
            # locations then).  Submitting again would run the task twice
            # concurrently and burn two budget units for one death.
            return True
        # ---- budget: reconstructions are capped per object AND draw down
        # the spec's retries_left, so crash-retries + reconstruction share
        # one budget (reference: task max_retries bounds both).
        if (st.recon_attempts >= config.max_object_reconstructions
                or spec.retries_left <= 0):
            return False
        # Dependency check BEFORE resetting the return objects: an
        # unrecoverable dep aborts reconstruction, and sibling returns
        # that are still sealed (e.g. in the local store) must keep their
        # status — resetting them first would strand them "pending".
        for dep in spec.dependency_ids():
            if not self._dep_recoverable(dep, store, _depth):
                return False
        for rid in spec.return_ids():
            s2 = self._obj(rid)
            if s2.status in ("store", "remote"):
                s2.status = "pending"
                s2.locations = []
                # the re-run may produce different bytes (nondeterministic
                # task): stale sizes must not skip the next pull's META
                s2.size = 0
                s2.remote_inline = False
        for rid in spec.return_ids():
            self._obj(rid).recon_attempts += 1
        spec.retries_left -= 1
        spec._acquired_pool = None
        spec._spill_count = 0  # fresh placement budget for the re-run
        self._m_recon_attempts += 1
        if self._im is not None:
            self._im["recon_depth"].observe(_depth)
        if _tracing.tracing_enabled():
            # recovery spans parent under the request that produced the
            # lost object (its ctx rides the retained creating spec)
            self._recon_trace[spec.task_id] = (time.time(), spec.trace_ctx,
                                               oid.hex())
        self._reconstructing.add(spec.task_id)
        self.async_get(spec.return_ids(),
                       lambda results, s=spec: self._on_recon_done(s, results))
        self._record_event(spec, "RECONSTRUCTING", depth=_depth)
        self.submit_task(spec)
        return True

    def _on_recon_done(self, spec: TaskSpec, results: Dict[str, tuple]):
        """All returns of a reconstruction attempt resolved (sealed or
        errored) — close out the attempt and count the outcome."""
        self._reconstructing.discard(spec.task_id)
        failed = any(r[0] == "error" for r in results.values())
        rec = self._recon_trace.pop(spec.task_id, None)
        if rec is not None:
            t0, ctx, oid_hex = rec
            _tracing.hop(f"recovery.reconstruct {spec.name}", ctx, t0,
                         time.time(),
                         status="ERROR" if failed else "OK",
                         proc="raylet", object_id=oid_hex,
                         task_id=spec.task_id.hex())
            self._arm_trace_flush()
        if failed:
            self._m_recon_failures += 1
        else:
            self._m_recon_successes += 1
            self._record_event(spec, "RECONSTRUCTED")

    # ------------------------------------------- eager replication
    # (cheap availability: recovery should be a copy, not a recompute —
    # reference: secondary object copies, SURVEY §3 object manager / §5
    # failure recovery.  The push rides the PR 4 data plane: the producer
    # asks the target to PULL, so striping/admission/failover all reuse
    # the pull manager.)

    def _maybe_replicate(self, oid: ObjectID, force: bool = False,
                         trace_ctx: Optional[dict] = None):
        """Push secondary copies of a locally sealed store object when it
        crosses the auto-threshold (RAY_TPU_REPLICATION_MIN_BYTES) or was
        explicitly flagged (``force``: _replicate option / checkpoint).
        ``trace_ctx``: the producing request's span context — the
        replication push shows up in its waterfall."""
        if not self.cluster_mode:
            return
        st = self._objects.get(oid)
        if st is None or st.status != "store" or st.replicated:
            return
        thresh = config.replication_min_bytes
        if not force and (thresh <= 0 or (st.size or 0) < thresh):
            return
        t0 = time.time() if _tracing.tracing_enabled() else 0.0
        sent = self._replicate_object(oid, st,
                                      max(1, config.replication_factor) - 1)
        if t0 and sent:
            _tracing.hop(f"recovery.replicate {oid.hex()[:8]}", trace_ctx,
                         t0, time.time(), proc="raylet", oid=oid.hex(),
                         targets=sent, bytes=st.size or 0)
            self._arm_trace_flush()

    def _replicate_object(self, oid: ObjectID, st: "_ObjectState",
                          count: int, exclude=(), attempt: int = 0) -> int:
        """Ask up to ``count`` live peers (none of which hold the object)
        to pull a copy from this node.  Pushes are fire-and-forget, so a
        delayed verify pass re-checks the directory and re-pushes when a
        target never registered its copy (died mid-pull, store-less,
        abandoned pull) — without it a silently failed push would leave
        the object unprotected forever while marked replicated."""
        if count <= 0:
            return 0
        have = {self.node_id} | set(st.locations) \
            | set(st.replicas or ()) | set(exclude)
        cands = [n for n, info in self._cluster_nodes.items()
                 if n not in have and info.get("alive", True)
                 # never push availability copies at a node that is itself
                 # suspected dead or being drained away
                 and not info.get("suspect") and not info.get("draining")
                 # a node registered WITHOUT a store can't hold a replica
                 # (node_added pushes lack the key: treat unknown as ok)
                 and (info.get("store_path") or "store_path" not in info)]
        if not cands:
            return 0
        random.shuffle(cands)
        sent = 0
        for target in cands:
            if sent >= count:
                break
            peer = self._get_peer(target)
            if peer is None:
                continue
            try:
                peer.send({"t": "xreplicate", "id": oid.hex(),
                           "size": st.size or 0, "src": self.node_id})
            except OSError:
                self._drop_peer(peer)
                continue
            if st.replicas is None:
                st.replicas = []
            st.replicas.append(target)
            sent += 1
            self._m_repl_pushes += 1
            self._m_repl_bytes += st.size or 0
        if sent:
            st.replicated = True
            if attempt < 2:
                self.add_timer(
                    max(0.5, config.replication_verify_delay_s),
                    lambda: self._verify_replication(oid, attempt + 1))
        return sent

    def _verify_replication(self, oid: ObjectID, attempt: int):
        """Delayed confirmation of a push round: targets that never
        registered their copy are scrubbed and replaced (bounded
        rounds).  An extra copy from a slow-but-successful pull racing
        the verify is tolerated — over-replication wastes a little
        store space, under-replication breaks the availability story."""
        st = self._objects.get(oid)
        if st is None or st.status != "store" or not st.replicated:
            return
        loc = self._gcs_err_ok(self.gcs.get_object_locations, oid.hex())
        if loc is _GCS_ERR:
            return
        registered = set((loc or {}).get("replicas", ()))
        st.replicas = sorted(registered - {self.node_id})
        self._repair_replication(oid, st, loc or {}, attempt=attempt)

    def _repair_replication(self, oid: ObjectID, st: "_ObjectState",
                            loc: dict, dead: Optional[str] = None,
                            attempt: int = 0) -> int:
        """Push enough fresh copies to restore the target count.  The
        deficit counts MANAGED copies only (directory ``replicas`` plus
        this primary): incidental consumer-side caches in ``nodes`` are
        transient, and counting them as durable copies would silently
        skip the repair right until they evict.  Current holders (caches
        included) are still excluded as push TARGETS — they already
        have the bytes."""
        nodes = set(loc.get("nodes", ()))
        managed = set(loc.get("replicas", ())) | {self.node_id}
        if dead is not None:
            managed.discard(dead)
        deficit = max(1, config.replication_factor) - len(managed)
        if deficit <= 0:
            return 0
        return self._replicate_object(oid, st, deficit, exclude=nodes,
                                      attempt=attempt)

    def _handle_xreplicate(self, msg: dict):
        """A peer sealed an object and wants a secondary copy here: pull
        it through the normal machinery (data plane when available).  The
        seal path marks the copy as a replica (``_replicating``)."""
        if not self.store_path:
            return  # store-less node: nowhere to hold a replica
        oid = ObjectID.from_hex(msg["id"])
        st = self._obj(oid)
        if st.status in ("inline", "store", "error"):
            return  # already local (or failed): nothing to do
        self._replicating.add(oid)
        src = msg.get("src")
        if src and src not in st.locations:
            st.locations.append(src)
        st.size = max(st.size, msg.get("size", 0))
        if st.status == "pending":
            st.status = "remote"
        self._maybe_pull(oid)

    def _handle_xreplica_drop(self, msg: dict):
        """The producer freed the primary: drop the managed replica —
        unless local work picked up references to it in the meantime, in
        which case it demotes to an ordinary refcounted entry."""
        oid = ObjectID.from_hex(msg["id"])
        st = self._objects.get(oid)
        if st is None:
            return
        if (st.holders > 0 or st.pins > 0 or oid in self._dep_index
                or oid in self._object_waiters):
            st.replicated = False
            return
        self.drop_object(oid)

    # --------------------------------------------- actor checkpoints

    def _on_actor_checkpoint(self, conn: _WorkerConn, msg: dict):
        """A checkpointable actor's worker snapshotted its state: seal the
        checkpoint object here, replicate it, and record it on the actor
        (relaying to the owner when the actor executes here for another
        raylet)."""
        oid = ObjectID.from_hex(msg["id"])
        inline = msg.get("inline")
        actor = (self._actors.get(conn.actor_id)
                 if conn.actor_id is not None else None)
        if actor is None or actor.conn is not conn:
            # Stale (buffered bytes from a conn whose actor already died
            # or restarted elsewhere): REJECT before sealing — a sealed
            # checkpoint nobody records would never be pinned, tracked,
            # or dropped, leaking its store bytes plus cluster replicas.
            if inline is None:
                store = self._raylet_store()
                if store is not None:
                    try:
                        store.delete(oid)  # scrub the dead worker's bytes
                    except Exception:  # noqa: BLE001
                        pass
            return
        if inline is not None:
            self._object_inline(oid, inline)
        else:
            st = self._obj(oid)
            st.size = max(st.size, msg.get("size", 0))
            self._object_in_store(oid)
            # checkpoints are the canonical "hot state worth a copy":
            # replicate regardless of the size threshold
            self._maybe_replicate(oid, force=True)
        if actor.foreign_owner is not None:
            # Exec side of a forwarded actor: the owner runs the restart
            # machine — ship the checkpoint ref (and the blob for inline
            # ones) to it; store checkpoints advertise this holder.  The
            # exec side ALSO records the snapshot locally (publish=False):
            # without the pin/track/supersede cycle every superseded
            # checkpoint object sealed here (plus its forced replicas)
            # would leak — only tracked entries ever free, and only the
            # primary's teardown drops replicas.
            self._set_actor_checkpoint(actor, oid, msg["seq"],
                                       publish=False)
            peer = self._get_peer(actor.foreign_owner)
            if peer is not None:
                try:
                    peer.send({"t": "xcheckpoint",
                               "actor_id": actor.actor_id,
                               "seq": msg["seq"], "id": msg["id"],
                               "inline": inline,
                               "size": msg.get("size", 0),
                               "node": self.node_id})
                except OSError:
                    self._drop_peer(peer)
            return
        self._set_actor_checkpoint(actor, oid, msg["seq"])

    def _handle_xcheckpoint(self, msg: dict):
        """Owner side: a forwarded actor checkpointed on its exec node.
        Staleness check FIRST (a relay from a node the actor already
        moved off): sealing or registering a checkpoint nobody records
        would leak an untracked, unpinned entry — the same hazard the
        exec-side stale path rejects before sealing."""
        actor = self._actors.get(msg["actor_id"])
        if actor is None or actor.node_id != msg.get("node"):
            return
        oid = ObjectID.from_hex(msg["id"])
        if msg.get("inline") is not None:
            self._object_inline(oid, msg["inline"])
        else:
            st = self._obj(oid)
            if msg.get("node") and msg["node"] not in st.locations:
                st.locations.append(msg["node"])
            st.size = max(st.size, msg.get("size", 0))
            if st.status == "pending":
                st.status = "remote"
            # keep a local copy too: the restart usually lands here, and
            # the exec node (the likeliest casualty) must not hold the
            # only bytes
            self._maybe_pull(oid)
        self._set_actor_checkpoint(actor, oid, msg["seq"])

    def _set_actor_checkpoint(self, actor: "_ActorState", oid: ObjectID,
                              seq: int, publish: bool = True):
        """Record the freshest checkpoint (callers already rejected stale
        sources by conn/node identity; ``seq`` is the worker's own count,
        kept for observability — the owner's counter is what orders
        snapshots across restarts).  ``publish=False`` on the exec side
        of a forwarded actor: pin/supersede locally, but the OWNER owns
        the GCS actor-table entry and the restart machine."""
        prev = actor.checkpoint_oid
        actor.checkpoint_oid = oid
        actor.checkpoint_seq += 1
        st = self._obj(oid)
        st.pins += 1        # the raylet holds the latest checkpoint
        st.tracked = True   # ...and superseded ones become freeable
        if publish:
            # owner-side only: the cluster-wide sum stays one per
            # snapshot even when exec + owner both record it
            self._m_ckpt_saves += 1
            self._m_ckpt_bytes += st.size or len(st.value or b"")
        if publish and self.cluster_mode:
            self._gcs_post("update_actor", actor.actor_id.binary(),
                           "alive", checkpoint=oid.hex(),
                           checkpoint_seq=actor.checkpoint_seq)
        if prev is not None and prev != oid:
            pst = self._objects.get(prev)
            if pst is not None:
                pst.pins -= 1
                self._maybe_free(prev)

    def _release_actor_checkpoint(self, actor: "_ActorState"):
        """Final actor death: the raylet's pin on the last checkpoint is
        released so it can free like any other unreferenced object."""
        oid = actor.checkpoint_oid
        if oid is None:
            return
        actor.checkpoint_oid = None
        st = self._objects.get(oid)
        if st is not None:
            st.pins -= 1
            self._maybe_free(oid)

    # --------------------------------------------------------------- streams

    def _init_stream(self, spec: TaskSpec):
        tid = spec.task_id
        if tid in self._streams:
            return
        self._streams[tid] = {"produced": 0, "total": None, "error": None,
                              "waiters": {}}
        # the completion marker resolves (count or error) through the same
        # object machinery every other return uses
        self.async_get(spec.return_ids(),
                       lambda results, t=tid: self._on_stream_done(t, results))

    def _on_stream_item(self, msg: dict):
        """A generator task yielded item #index (worker message)."""
        oid = ObjectID.from_hex(msg["id"])
        if msg.get("inline") is not None:
            self._object_inline(oid, msg["inline"],
                                contains=msg.get("contains"))
        else:
            self._obj(oid).size = msg.get("size", 0)
            self._object_in_store(oid, contains=msg.get("contains"))
        tid = oid.task_id()
        origin = self._foreign_streams.get(tid)
        if origin is not None:
            # executing for another raylet: relay the item so the
            # consumer-side stream advances (store items transfer lazily
            # via the normal pull path)
            peer = self._get_peer(origin)
            if peer is not None:
                relay = dict(msg)
                relay["t"] = "xstream_item"
                if msg.get("inline") is None:
                    relay["location"] = self.node_id
                try:
                    peer.send(relay)
                except OSError:
                    self._drop_peer(peer)
        self._advance_stream(tid, msg["index"])

    def _handle_xstream_item(self, msg: dict):
        """Relayed stream item from the executing node."""
        oid = ObjectID.from_hex(msg["id"])
        if msg.get("inline") is not None:
            self._object_inline(oid, msg["inline"])
        else:
            st = self._obj(oid)
            if st.status == "pending":
                st.status = "remote"
                st.size = msg.get("size", 0)
                st.locations = [msg["location"]]
                self._object_ready(oid)
        tid = oid.task_id()
        onward = self._foreign_streams.get(tid)
        if onward is not None:
            # 3-hop case (consumer -> actor owner -> exec node): keep
            # relaying toward the consumer
            peer = self._get_peer(onward)
            if peer is not None:
                try:
                    peer.send({**msg, "t": "xstream_item"})
                except OSError:
                    self._drop_peer(peer)
        self._advance_stream(tid, msg["index"])

    def _advance_stream(self, tid: TaskID, index: int):
        st = self._streams.get(tid)
        if st is None:
            return
        st["produced"] = max(st["produced"], index + 1)
        for idx in [i for i in st["waiters"] if i < st["produced"]]:
            for cb in st["waiters"].pop(idx):
                self._safe(lambda cb=cb: cb({"kind": "item"}))

    def _on_stream_done(self, tid: TaskID, results: Dict[str, tuple]):
        self._foreign_streams.pop(tid, None)
        st = self._streams.get(tid)
        if st is None:
            return
        marker = next(iter(results.values()))
        if marker[0] == "error":
            st["error"] = marker[1]
        else:
            st["total"] = st["produced"]
        for idx in list(st["waiters"]):
            for cb in st["waiters"].pop(idx):
                if idx < st["produced"]:
                    # already-produced items stay consumable even when the
                    # generator errored later
                    self._safe(lambda cb=cb: cb({"kind": "item"}))
                elif st["error"] is not None:
                    self._safe(lambda cb=cb: cb(
                        {"kind": "error", "error": st["error"]}))
                else:
                    self._safe(lambda cb=cb: cb({"kind": "end"}))
        # GC: consumers may lag; the state (a tiny dict) lingers for a
        # grace period, then goes away (reference ties this to generator
        # ref counting).
        self.add_timer(300.0, lambda: self._streams.pop(tid, None))

    def async_stream_next(self, tid: TaskID, index: int, cb: Callable):
        """cb receives {"kind": "item" | "end" | "error", ...}.  Returns a
        cancel callable or None when answered synchronously."""
        st = self._streams.get(tid)
        if st is None:
            cb({"kind": "error",
                "error": ValueError(f"unknown stream {tid.hex()}")})
            return None
        if index < st["produced"]:
            cb({"kind": "item"})
            return None
        if st["error"] is not None:
            cb({"kind": "error", "error": st["error"]})
            return None
        if st["total"] is not None:
            cb({"kind": "end"})
            return None
        st["waiters"].setdefault(index, []).append(cb)

        def cancel():
            lst = st["waiters"].get(index)
            if lst and cb in lst:
                lst.remove(cb)
                if not lst:
                    del st["waiters"][index]

        return cancel

    # --------------------------------------------------------------- objects

    def _obj(self, oid: ObjectID) -> _ObjectState:
        st = self._objects.get(oid)
        if st is None:
            st = _ObjectState()
            self._objects[oid] = st
        return st

    def _set_contains(self, st: "_ObjectState", contains):
        """Record + pin the refs serialized inside this object's bytes;
        released when the entry itself is freed."""
        if not contains:
            return
        if st.contains:
            # re-seal (retry/reconstruction): drop the old pins first
            for inner in st.contains:
                inner_st = self._objects.get(inner)
                if inner_st is not None:
                    inner_st.pins -= 1
                    self._maybe_free(inner)
        st.contains = list(contains)
        for inner in st.contains:
            self._obj(inner).pins += 1

    def _object_inline(self, oid: ObjectID, blob: bytes, contains=None):
        st = self._obj(oid)
        st.status = "inline"
        st.value = blob
        st.size = len(blob)
        self._set_contains(st, contains)
        if self.cluster_mode:
            self._gcs_post("add_object_location", oid.hex(),
                           self.node_id, len(blob), inline=True,
                           incarnation=self.incarnation)
        self._object_ready(oid)

    def _object_in_store(self, oid: ObjectID, contains=None):
        st = self._obj(oid)
        st.status = "store"
        self._set_contains(st, contains)
        replica = oid in self._replicating
        if replica:
            # This seal completed an eager-replication pull: mark the copy
            # managed (this node re-replicates on holder death) and tell
            # the directory it is a secondary.
            self._replicating.discard(oid)
            st.replicated = True
        if self.cluster_mode:
            self._gcs_post("add_object_location", oid.hex(),
                           self.node_id, st.size, replica=replica,
                           incarnation=self.incarnation)
        self._object_ready(oid)

    def _object_error(self, oid: ObjectID, err: Exception):
        st = self._obj(oid)
        st.status = "error"
        st.error = err
        self._object_ready(oid)

    def _object_ready(self, oid: ObjectID):
        st = self._objects.get(oid)
        status = st.status if st is not None else "pending"
        dep_error = st.error if (st is not None and st.status == "error") else None
        # unblock dependent tasks
        waiting = self._dep_index.pop(oid, None)
        if waiting:
            for task_id in list(waiting):
                entry = self._waiting.get(task_id)
                if entry is None:
                    continue
                spec, missing = entry
                if dep_error is not None:
                    # An errored dependency fails the dependent immediately
                    # (reference: RayTaskError propagates through deps) —
                    # never dispatch a task whose arg can only time out.
                    del self._waiting[task_id]
                    for m in missing:
                        peers = self._dep_index.get(m)
                        if peers:
                            peers.discard(task_id)
                    for rid in spec.return_ids():
                        self._object_error(rid, dep_error)
                    self._record_event(spec, "FAILED", dep_error=True)
                    continue
                missing.discard(oid)
                if not missing:
                    del self._waiting[task_id]
                    self._enqueue_ready(spec)
        # fire get/wait callbacks — only when LOCALLY resolved; a "remote"
        # transition keeps waiters registered (they resolve when the pull
        # seals the object here) but must kick the pull off.
        if status in ("inline", "store", "error"):
            st.lookup_attempts = 0  # backoff resets once materialized
            for cb in self._object_waiters.pop(oid, []):
                self._safe(lambda cb=cb: cb(oid))
        elif status == "remote" and oid in self._object_waiters:
            self._maybe_pull(oid)
        self._maybe_free(oid)  # nobody may have held it by now
        self._schedule()

    def _object_status(self, oid: ObjectID) -> str:
        st = self._objects.get(oid)
        return st.status if st else "pending"

    # --------------------------------------------------------------- submission

    def submit_task(self, spec: TaskSpec, foreign_origin: Optional[str] = None):
        """Entry point for driver and nested worker submissions.

        ``foreign_origin``: this spec was forwarded here by another raylet
        (which stays the owner of actors and handles restarts); skip the
        owner-side registrations.
        """
        if spec.trace_ctx is not None:
            # inbox-receipt timestamp: the first lifecycle transition
            # closes the raylet.inbox hop span.  A forwarded spec re-opens
            # it here (fresh node, fresh inbox interval).
            spec._tr_in = time.time()
            spec._tr_prev = None
        if getattr(spec, "_direct_retry", False) and all(
                self._object_status(o) in ("inline", "store", "error")
                for o in spec.return_ids()):
            # Reconcile of an in-flight direct call whose result DID land
            # (the direct_done raced the channel teardown): already
            # resolved — never execute twice.
            return
        self._note_child(spec)
        flag = self._cancelled_flag(spec)
        if flag is not None and spec.kind != ACTOR_CREATION_TASK:
            # This task (or the parent that spawned it) was already reaped
            # by a cancel/deadline fan-out — its submit frame raced the
            # fan-out here.  Drop it at the door, and remember IT so its
            # own late-arriving children are caught too.
            self._note_cancelled(spec.task_id, flag)
            if flag:
                self._m_deadline_exceeded += 1
                self._shed_spec(spec, DeadlineExceededError(
                    f"task {spec.name} parent deadline already expired",
                    hop="raylet.admission"), "EXPIRED", hop="admission")
            else:
                self._m_cancelled += 1
                self._shed_spec(spec, TaskCancelledError(
                    f"task {spec.name} was cancelled before it ran"),
                    "CANCELLED")
            return
        if config.deadlines and spec.deadline is not None \
                and spec.kind != ACTOR_CREATION_TASK:
            # Admission control: an already-expired request is dropped at
            # the door — no dep pinning, no lineage, no queue slot, no
            # wasted exec (reference: Serve request timeouts shed before
            # the replica sees the request).
            remaining = spec.deadline - time.time()
            if remaining <= 0:
                self._m_deadline_exceeded += 1
                err = DeadlineExceededError(
                    f"task {spec.name} deadline expired before admission",
                    hop="raylet.admission")
                for oid in spec.return_ids():
                    self._object_error(oid, err)
                self._record_event(spec, "EXPIRED", hop="admission",
                                   error=self._err_summary(err))
                return
            # Expiry timer: fires while the task is still queued anywhere
            # on this node (waiting on args, ready queue, actor queue) —
            # running tasks are interrupted by the worker-side watchdog,
            # and a completed task makes this a no-op.  Captures ids
            # only: a closure over the spec would pin its arg payloads
            # in the timer heap for the whole deadline window even after
            # the task completes.
            self.add_timer(
                remaining + 0.01,
                lambda t=spec.task_id, o=spec.return_ids(), n=spec.name:
                self._on_deadline(t, o, n))
        # Lineage for eviction recovery: NORMAL tasks only (actor results
        # aren't replayable) and bounded — beyond the cap new objects lose
        # reconstructability instead of the raylet growing without limit
        # (reference bounds lineage bytes, ray_config_def.h lineage caps).
        keep_lineage = (spec.kind == NORMAL_TASK
                        and self._lineage_count < config.max_lineage_entries)
        for oid in spec.return_ids():
            st = self._obj(oid)
            if keep_lineage and st.creating_spec is None:
                st.creating_spec = spec
                self._lineage_count += 1
        self._pin_deps(spec)
        if spec.num_returns == STREAMING_RETURNS:
            self._init_stream(spec)
        if spec.kind == ACTOR_CREATION_TASK:
            actor = _ActorState(spec, name=(spec.placement or {}).get("name"))
            self._actors[spec.actor_id] = actor
            # direct-transport fencing: the creation spec carries the
            # generation the hosted worker will validate hellos against
            actor.generation = getattr(spec, "_direct_generation", 0)
            spec._direct_generation = actor.generation
            if foreign_origin is not None:
                # exec-side state: the owner restarts, we only report deaths
                actor.restarts_left = 0
                actor.foreign_owner = foreign_origin
            else:
                namespace = (spec.placement or {}).get("namespace", "")
                if actor.name or self.cluster_mode:
                    import cloudpickle as _cp

                    ok = self._gcs_safe(
                        self.gcs.register_actor, spec.actor_id.binary(),
                        self.node_id, name=actor.name, namespace=namespace,
                        spec_blob=_cp.dumps(spec) if actor.name else None,
                        incarnation=self.incarnation)
                    if ok is False:
                        del self._actors[spec.actor_id]
                        err = ValueError(
                            f"actor name {actor.name!r} already taken")
                        for oid in spec.return_ids():
                            self._object_error(oid, err)
                        return
        missing = {
            oid for oid in spec.dependency_ids()
            if self._object_status(oid) not in ("inline", "store", "remote")
        }
        # error deps propagate immediately
        for oid in list(missing):
            if self._object_status(oid) == "error":
                err = self._objects[oid].error
                for rid in spec.return_ids():
                    self._object_error(rid, err)
                self._record_event(spec, "FAILED", dep_error=True,
                                   error=self._err_summary(err))
                return
        if missing:
            # QUEUED is recorded by _enqueue_ready once the args resolve
            self._record_event(spec, "PENDING_ARGS")
            self._waiting[spec.task_id] = (spec, missing)
            for oid in missing:
                self._dep_index.setdefault(oid, set()).add(spec.task_id)
            if self.cluster_mode:
                # A dep produced on another node resolves via the GCS
                # directory watch the pull registers.
                for oid in missing:
                    self._maybe_pull(oid, priority=0,  # task args
                                     trace_ctx=spec.trace_ctx)
        else:
            self._enqueue_ready(spec)
        self._schedule()

    def _enqueue_ready(self, spec: TaskSpec):
        spec._queued_t = time.monotonic()  # dispatch-latency metric start
        self._record_event(spec, "QUEUED")
        if spec.kind == ACTOR_TASK:
            actor = self._actors.get(spec.actor_id)
            if actor is None:
                if self.cluster_mode and self._route_foreign_actor_task(spec):
                    return
                err = ActorDiedError(
                    spec.actor_id.hex() if spec.actor_id else "?",
                    "unknown actor",
                )
                for oid in spec.return_ids():
                    self._object_error(oid, err)
                self._record_event(spec, "FAILED",
                                   error=self._err_summary(err))
                return
            if actor.state == "dead":
                err = ActorDiedError(
                    spec.actor_id.hex() if spec.actor_id else "?",
                    actor.death_reason,
                )
                for oid in spec.return_ids():
                    self._object_error(oid, err)
                self._record_event(spec, "FAILED",
                                   error=self._err_summary(err))
                return
            if (getattr(spec, "_direct_retry", False)
                    and spec._direct_generation != actor.generation):
                # Reconcile of an in-flight direct call from BEFORE the
                # actor's last restart: the old incarnation may have run
                # it (and died before the result escaped) — executing it
                # on the restarted instance could double side effects, so
                # it fails like any other interrupted in-flight call.
                err = ActorDiedError(
                    spec.actor_id.hex() if spec.actor_id else "?",
                    "actor restarted while a direct call was in flight "
                    "(restarting)")
                for oid in spec.return_ids():
                    self._object_error(oid, err)
                self._record_event(spec, "FAILED", direct=True,
                                   error=self._err_summary(err))
                return
            depth = config.max_queue_depth
            if (depth > 0 and len(actor.queue) >= depth
                    and self._shed_lowest_headroom(
                        actor.queue, spec, "actor queue")):
                return
            actor.queue.append(spec)
            self._pump_actor(actor)
        else:
            depth = config.max_queue_depth
            if (depth > 0 and spec.kind == NORMAL_TASK
                    and len(self._ready_queue) >= depth
                    and self._shed_lowest_headroom(
                        self._ready_queue, spec, "ready queue")):
                return
            self._ready_queue.append(spec)

    def _route_foreign_actor_task(self, spec: TaskSpec) -> bool:
        """An actor task for an actor owned by another raylet (its handle
        travelled here inside args / via get_actor): forward to the owner."""
        owner = self._actor_owner_cache.get(spec.actor_id)
        if owner is None:
            info = self._gcs_safe(self.gcs.get_actor, spec.actor_id.binary())
            if not info:
                return False
            owner = info["owner_node"]
            self._actor_owner_cache[spec.actor_id] = owner
        if owner == self.node_id:
            return False
        if getattr(spec, "_spill_count", 0) >= config.spillback_max_hops:
            return False  # routing loop guard (stale owner metadata)
        return self._forward_task(spec, owner)

    # --------------------------------------------------------------- scheduling

    def _task_resource_pools(self, spec: TaskSpec):
        """Return (avail_dict, need) — node pool or placement-group bundle."""
        placement = spec.placement or {}
        pg_hex = placement.get("pg")
        if pg_hex:
            pg = self._pgs.get(pg_hex)
            if pg is None or pg.state != "created":
                return None, None
            idx = placement.get("bundle", 0)
            if idx == -1:
                for b in pg.available.values():
                    if _fits(b, spec.resources):
                        return b, spec.resources
                return None, spec.resources
            pool = pg.available.get(idx)
            if pool is None:
                return None, None  # bundle lives on another node's fragment
            return pool, spec.resources
        return self.resources_available, spec.resources

    def _release_task_resources(self, spec: TaskSpec):
        batch = getattr(spec, "_batch", None)
        if batch is not None:
            # sequential dispatch batch: the batch holds ONE task's
            # resources, released when its last member finishes (done,
            # death, or requeue — each path comes through here exactly
            # once per member).
            spec._batch = None
            batch["open"] -= 1
            if batch["open"] == 0:
                _release(batch["pool"], batch["need"])
            return
        pool = getattr(spec, "_acquired_pool", None)
        if pool is not None:
            _release(pool, spec.resources)
            spec._acquired_pool = None

    def _dep_errored(self, spec: TaskSpec) -> bool:
        """If any dependency of a ready task has since errored, fail the task
        now instead of dispatching it to block on an arg that never comes."""
        for oid in spec.dependency_ids():
            st = self._objects.get(oid)
            if st is not None and st.status == "error":
                if spec.restore_oid is not None and oid == spec.restore_oid:
                    # an unrecoverable CHECKPOINT must not kill the actor:
                    # fall back to a cold start (the cost checkpointing
                    # exists to avoid, but strictly better than dead)
                    spec.restore_oid = None
                    continue
                for rid in spec.return_ids():
                    self._object_error(rid, st.error)
                self._record_event(spec, "FAILED", dep_error=True)
                return True
        return False

    def _activate_pending_pgs(self):
        """Reserve bundles for queued placement groups as resources free up
        (reference queues infeasible PGs instead of oversubscribing)."""
        for pg in self._pgs.values():
            if pg.state != "pending":
                continue
            if pg.fragment:
                for i in sorted(pg.unreserved):
                    if _fits(self.resources_available, pg.bundles[i]):
                        _acquire(self.resources_available, pg.bundles[i])
                        pg.unreserved.discard(i)
                if pg.unreserved:
                    continue
            else:
                total = pg.total()
                if not _fits(self.resources_available, total):
                    continue
                _acquire(self.resources_available, total)
                pg.unreserved.clear()
            pg.state = "created"
            if pg.fragment:
                self._gcs_post("pg_fragment_ready", pg.pg_id,
                               self.node_id)
            if pg.ready_oid is not None:
                self._object_inline(pg.ready_oid, _PG_READY_BLOB)

    def _schedule(self):
        """Request a scheduling pass (coalesced; see _run)."""
        self._need_schedule = True

    def _schedule_now(self):
        self._activate_pending_pgs()
        if not self._ready_queue:
            return
        # Fast bail: with zero idle workers and every near-head profile's
        # pool already at the per-profile spawn cap, a pass can neither
        # dispatch nor usefully spawn — and done-storms request one pass
        # per completion batch, so the deferred-queue rotation below would
        # run O(completions) times.  Actor tasks in the ready queue (retry
        # rejoin path) always force a full pass — they route through the
        # actor machinery, not the worker pool.
        if (not self.cluster_mode
                and not any(self._idle.values())):
            cap = max(1, int(self.resources_total.get("CPU", 1) or 1))
            poolable: Dict[str, int] = {}
            for c in self._workers.values():
                if c.actor_id is None and c.state in ("idle", "busy"):
                    poolable[c.profile] = poolable.get(c.profile, 0) + 1
            for prof, n in self._spawning.items():
                poolable[prof] = poolable.get(prof, 0) + n
            # Window = the full pass's no-progress bound: entries beyond it
            # were unreachable in a defer-storm pass anyway, so the bail
            # never hides work a full pass would have found.
            can_bail = True
            for s in itertools.islice(self._ready_queue, 128):
                if (s.kind == ACTOR_TASK
                        or poolable.get(self._profile_key(s), 0) < cap):
                    can_bail = False
                    break
            if can_bail:
                # every completion calls _schedule(), so the next pass is
                # already guaranteed once a worker frees
                return
        deferred = deque()
        spawn_demand: Dict[str, int] = {}
        pg_orphans = []  # tasks whose PG no longer exists — fail after drain
        # Bounded scan: once NO_PROGRESS_WINDOW consecutive specs deferred
        # without a single dispatch, stop — freed capacity this pass is
        # exhausted and rescanning a 10k-deep queue per completion batch is
        # O(n^2).  (The reference keeps per-resource-shape queues instead;
        # heterogeneous head-of-line blocking within the window is the
        # accepted trade.)
        no_progress = 0
        NO_PROGRESS_WINDOW = 128
        spill_queries = 0  # GCS placement lookups per pass (round trips)
        # Shapes that already failed THIS pass (no free resources or no
        # idle worker): later queued tasks with the same shape defer
        # without re-running the full placement body — the deep-queue scan
        # was the submission-throughput hot spot (profiled: 72k _fits
        # calls for 2k tasks).
        failed_shapes: set = set()
        while self._ready_queue:
            if no_progress >= NO_PROGRESS_WINDOW:
                break
            spec = self._ready_queue.popleft()
            if self._dep_errored(spec):
                continue
            if self._deadline_expired(spec):
                # pre-dispatch check: a task that expired while queued is
                # dropped before it costs a worker (typed result, no exec)
                self._m_deadline_exceeded += 1
                self._shed_spec(spec, DeadlineExceededError(
                    f"task {spec.name} deadline expired in the ready queue",
                    hop="raylet.pre_dispatch"), "EXPIRED", hop="pre_dispatch")
                self._cancel_children(spec.task_id, deadline=True)
                continue
            if (not spec.placement and spec.kind == NORMAL_TASK
                    and not self.cluster_mode):
                shape_key = tuple(sorted((spec.resources or {}).items()))
                if shape_key in failed_shapes:
                    deferred.append(spec)
                    no_progress += 1
                    continue
            else:
                shape_key = None
            if spec.kind == ACTOR_TASK:
                # An actor task can land in the ready queue via retry paths;
                # route it through the actor machinery.
                self._enqueue_ready(spec)
                continue
            placement = spec.placement or {}
            if self.cluster_mode:
                # Node affinity (reference: NodeAffinitySchedulingStrategy).
                aff = placement.get("node_id")
                if aff and aff != self.node_id:
                    if not self._forward_task(spec, aff):
                        deferred.append(spec)
                        no_progress += 1
                    continue
                # Draining: nothing new dispatches locally — forward
                # everything placeable to a surviving node (the GCS
                # placement already skips this node), so the drain
                # quiesces instead of re-filling.  Unforwardable work
                # defers and rides the drain deadline.
                if (self._draining and not placement.get("pg")
                        and spill_queries < 32):
                    spill_queries += 1
                    target = self._gcs_safe(
                        self.gcs.place_task, spec.resources or {},
                        exclude=[self.node_id])
                    if target and self._forward_task(spec, target):
                        continue
                    deferred.append(spec)
                    no_progress += 1
                    continue
                # Locality-aware placement (reference: locality_aware lease
                # policy): a task whose arguments hold more bytes on a peer
                # than here moves to the data instead of pulling the data.
                if (not placement and spec.kind == NORMAL_TASK
                        and getattr(spec, "_spill_count", 0)
                        < config.spillback_max_hops):
                    loc_target = self._locality_preferred_node(spec)
                    if loc_target is not None \
                            and self._forward_task(spec, loc_target):
                        self._m_locality_spills += 1
                        continue
            pool, need = self._task_resource_pools(spec)
            if pool is None:
                # Distinguish "not schedulable yet" (pending PG, full
                # bundles → defer) from "never schedulable" (PG removed or
                # unknown → fail now, else the task defers forever) from
                # "bundle on ANOTHER node's fragment" (cluster → forward).
                # _object_error re-enters _schedule, so only collect here.
                pg_hex = (spec.placement or {}).get("pg")
                idx = (spec.placement or {}).get("bundle", 0)
                local = self._pgs.get(pg_hex) if pg_hex else None
                if (local is not None and not local.fragment and idx != -1
                        and idx not in local.bundles):
                    # out-of-range bundle index on a whole local PG: fail
                    # loudly instead of deferring forever
                    err = ValueError(
                        f"bundle index {idx} out of range for placement "
                        f"group {pg_hex} ({len(local.bundles)} bundles)")
                    for rid in spec.return_ids():
                        self._object_error(rid, err)
                    self._record_event(spec, "FAILED", bad_bundle=True)
                    continue
                if pg_hex and self.cluster_mode and spill_queries < 8:
                    bundle_elsewhere = (
                        local is None
                        or (local.fragment
                            and (idx != -1 and idx not in local.available
                                 or idx == -1 and not any(
                                     _fits(b, spec.resources)
                                     for b in local.bundles.values()))))
                    if bundle_elsewhere:
                        spill_queries += 1
                        info = self._gcs_err_ok(self.gcs.pg_info, pg_hex)
                        if info is _GCS_ERR:
                            deferred.append(spec)  # transient GCS trouble
                            no_progress += 1
                            continue
                        if info is not None:
                            if info["state"] != "created":
                                deferred.append(spec)
                                no_progress += 1
                                continue
                            if idx != -1:
                                target = info["assignments"].get(idx)
                                if (target is None
                                        and idx >= len(info["bundles"])):
                                    err = ValueError(
                                        f"bundle index {idx} out of range "
                                        f"for placement group {pg_hex}")
                                    for rid in spec.return_ids():
                                        self._object_error(rid, err)
                                    self._record_event(spec, "FAILED",
                                                       bad_bundle=True)
                                    continue
                            else:
                                # any-bundle: pick a node whose ASSIGNED
                                # bundle can fit this task
                                target = next(
                                    (n for i2, n in sorted(
                                        info["assignments"].items())
                                     if _fits(dict(info["bundles"][i2]),
                                              spec.resources)), None)
                            if (target and target != self.node_id
                                    and self._forward_task(spec, target)):
                                continue
                            deferred.append(spec)
                            no_progress += 1
                            continue
                        # authoritative: the GCS has no such PG
                        if local is None:
                            pg_orphans.append(spec)
                            continue
                if pg_hex and pg_hex not in self._pgs \
                        and not self.cluster_mode:
                    # cluster mode orphans only via the GCS lookup above
                    pg_orphans.append(spec)
                    continue
                deferred.append(spec)
                no_progress += 1
                continue
            if not _fits(pool, need):
                # Spillback (reference: ClusterTaskManager picks another
                # node and the lease reply redirects the client,
                # cluster_task_manager.cc:418): when the task cannot run
                # here now but another node has capacity, forward it.
                if (self.cluster_mode
                        and not placement.get("pg")
                        and spill_queries < 8
                        and getattr(spec, "_spill_count", 0)
                        < config.spillback_max_hops):
                    spill_queries += 1
                    fits_total = _fits(self.resources_total, need)
                    target = self._gcs_safe(
                        self.gcs.place_task, need,
                        exclude=[self.node_id],
                        # locality hint: the GCS scores candidates by arg
                        # bytes already on them (object directory sizes)
                        arg_ids=[o.hex() for o in itertools.islice(
                            spec.dependency_ids(), 16)] or None)
                    if target is None and not fits_total:
                        # nowhere has capacity free now; if some node could
                        # EVER fit it, forward there to queue
                        feas = self._gcs_safe(self.gcs.feasible_nodes, need)
                        feas = [n for n in (feas or []) if n != self.node_id]
                        target = feas[0] if feas else None
                    if target and self._forward_task(spec, target):
                        continue
                if shape_key is not None:
                    failed_shapes.add(shape_key)
                deferred.append(spec)
                no_progress += 1
                continue
            if self._remote_deps_pending(spec):
                deferred.append(spec)  # pulls in flight; retried on seal
                no_progress += 1
                continue
            profile = self._profile_key(spec)
            conn = self._get_idle_worker(profile)
            if conn is None:
                spawn_demand[profile] = spawn_demand.get(profile, 0) + 1
                if shape_key is not None:
                    # same-shape tasks would also find no idle worker; the
                    # skip is per-pass only (any env-profile mismatch just
                    # re-evaluates next pass)
                    failed_shapes.add(shape_key)
                deferred.append(spec)
                no_progress += 1
                continue
            batch = [spec]
            # Fair share: never batch deeper than the queue spread over the
            # workers that could also take this shape — a fan-out of 8
            # tasks with 8 idle workers must not serialize onto one.
            # SPAWNABLE workers count too: batching the whole queue onto
            # the only live worker would consume the very backlog whose
            # no-idle-worker signal drives pool growth, freezing the pool
            # at its current size.
            idle_same = len(self._idle.get(profile, ()))
            pool_same = self._spawning.get(profile, 0) + sum(
                1 for c in self._workers.values()
                if c.actor_id is None and c.state in ("idle", "busy")
                and c.profile == profile)
            cpu_cap = max(1, int(self.resources_total.get("CPU", 1) or 1))
            spawnable = max(0, cpu_cap - pool_same)
            fair = -(-(len(self._ready_queue) + 1)
                     // (idle_same + spawnable + 1))
            batch_cap = min(config.dispatch_batch_max, fair)
            if (shape_key is not None and batch_cap > 1
                    and self._ready_queue):
                # Same-shape followers from the queue head ride the same
                # coalesced frame (ONE sendall — the syscall, not the
                # pickle, is the per-dispatch cost on a busy host) and
                # execute sequentially on this worker, so the whole batch
                # holds one task's resources.  Consecutive-head-only keeps
                # FIFO order; the first non-matching spec stops the batch.
                while (len(batch) < batch_cap
                       and self._ready_queue):
                    nxt = self._ready_queue[0]
                    if (nxt.kind != NORMAL_TASK or nxt.placement
                            or self._profile_key(nxt) != profile
                            or tuple(sorted((nxt.resources or {}).items()))
                            != shape_key):
                        break
                    self._ready_queue.popleft()
                    if self._dep_errored(nxt):
                        continue
                    if self._remote_deps_pending(nxt):
                        deferred.append(nxt)
                        continue
                    batch.append(nxt)
            _acquire(pool, need)
            if len(batch) == 1:
                spec._acquired_pool = pool
            else:
                rec = {"open": len(batch), "pool": pool, "need": need}
                for s in batch:
                    s._batch = rec
                    s._acquired_pool = None
            self._dispatch_many(batch, conn)
            no_progress = 0
        deferred.extend(self._ready_queue)  # early-break keeps the tail
        self._ready_queue = deferred
        for spec in pg_orphans:
            if spec.kind == ACTOR_CREATION_TASK and \
                    spec.actor_id in self._actors:
                # The actor was registered at submit time; erroring only
                # the creation refs would leave it 'pending' with method
                # calls queueing forever — mark it dead (same treatment
                # as remove_pg gives never-dispatched PG actors).
                actor = self._actors[spec.actor_id]
                actor.restarts_left = 0
                self._on_actor_death(
                    spec.actor_id,
                    f"placement group {(spec.placement or {}).get('pg')} "
                    "was removed", allow_restart=False)
                continue
            err = ValueError(
                f"placement group {(spec.placement or {}).get('pg')} "
                "was removed")
            for rid in spec.return_ids():
                self._object_error(rid, err)
            self._record_event(spec, "FAILED", pg_removed=True)
        # Spawn up to queue-depth workers per profile in one pass (reference
        # pops/starts a worker per pending lease, `worker_pool.h:156`) —
        # capped by node CPUs so a deep queue can't fork-bomb the host.
        # Note: actors hold their workers for life, so total workers may
        # legitimately exceed CPU count — the cap bounds the spawn *burst*,
        # not the pool size (resource accounting already gates dispatch).
        cap = max(1, int(self.resources_total.get("CPU", 1) or 1))
        poolable: Dict[str, int] = {}
        for c in self._workers.values():
            # real pool members only: driver conns (state "driver") and
            # not-yet-identified accepts share the dict but aren't workers
            if c.actor_id is None and c.state in ("idle", "busy"):
                poolable[c.profile] = poolable.get(c.profile, 0) + 1
        for profile, depth in spawn_demand.items():
            pending = self._spawning.get(profile, 0)  # includes unregistered
            # Cap the PROFILE'S POOL (existing poolable workers + in-flight
            # spawns), not just the per-pass burst: a deep queue must not
            # keep forking beyond CPU count while earlier workers are busy
            # (each spawn costs a Python+jax import).  Actors hold workers
            # for life and are excluded — resource accounting gates them.
            want = min(depth, cap - poolable.get(profile, 0)) - pending
            for _ in range(max(0, want)):
                self._spawn_worker(profile)

    def _locality_preferred_node(self, spec: TaskSpec) -> Optional[str]:
        """Node holding strictly more bytes of this task's arguments than
        are local here (and at least locality_aware_min_bytes) — the
        scheduler moves large-arg tasks to the data.  Sizes come from the
        object directory via xdone/object_at/pull metadata; unknown sizes
        count as zero (never force a GCS round trip per schedule pass)."""
        min_bytes = config.locality_aware_min_bytes
        if min_bytes <= 0:
            return None
        local = 0
        by_node: Dict[str, int] = {}
        for oid in spec.dependency_ids():
            st = self._objects.get(oid)
            if st is None:
                continue
            if st.status in ("inline", "store"):
                local += st.size or 0
            elif st.status == "remote" and not st.remote_inline:
                for n in st.locations:
                    by_node[n] = by_node.get(n, 0) + (st.size or 0)
        if not by_node:
            return None
        best, best_bytes = max(by_node.items(), key=lambda kv: kv[1])
        if best_bytes < min_bytes or best_bytes <= local:
            return None
        info = self._cluster_nodes.get(best)
        if info is None or info.get("suspect") or info.get("draining"):
            return None
        total = info.get("resources_total")
        # node_added pushes carry only id+address; with capacity unknown,
        # forward optimistically — an infeasible target spills the task
        # back (hop-capped) rather than suppressing locality entirely
        if total is not None and not _fits(total, spec.resources or {}):
            return None
        return best

    def _dispatch_msg(self, spec: TaskSpec, conn: _WorkerConn,
                      running: bool = True) -> dict:
        conn.state = "busy"
        conn.current_task = spec
        conn.task_start_time = time.monotonic()
        conn.inflight[spec.task_id] = spec
        if spec.kind == ACTOR_CREATION_TASK:
            conn.actor_id = spec.actor_id
            actor = self._actors[spec.actor_id]
            actor.conn = conn
        arg_values: Dict[str, bytes] = {}
        for oid in spec.dependency_ids():
            st = self._objects.get(oid)
            if st is not None and st.status == "inline":
                arg_values[oid.hex()] = st.value
        fn_blob = None
        if spec.function_id is not None:
            key = spec.function_id.binary()
            if spec.function_blob is not None and not self.cluster_mode:
                # Strip the inline blob off the wire spec: workers cache
                # the function by id after the first dispatch, so
                # re-pickling the blob for every task of a flood is pure
                # waste.  The blob moves to the GCS function table (the
                # local LRU below may evict it — a closure-minting driver
                # must not pin every blob in raylet memory) and the
                # export-once growth matches reference function-manager
                # semantics.  (Cluster mode keeps it inline — forwarded
                # specs must stay self-contained for peers.)
                if key not in self._fn_cache:
                    self._gcs_safe(self.gcs.put_function, key,
                                   spec.function_blob)
                    self._fn_cache[key] = spec.function_blob
                spec.function_blob = None
            if key not in conn.sent_fns:
                fn_blob = self._fn_cache.get(key)
                if fn_blob is None:
                    fn_blob = self._gcs_safe(self.gcs.get_function, key)
                    if fn_blob is not None:
                        self._fn_cache[key] = fn_blob
                if len(conn.sent_fns) > (1 << 16):
                    conn.sent_fns.clear()  # worker re-fetches; bounded set
                conn.sent_fns.add(key)
            if len(self._fn_cache) > 512:  # bounded write-through cache
                self._fn_cache.pop(next(iter(self._fn_cache)))
        # Batch followers queue ON the worker behind the head task: they
        # are DISPATCHED (shipped) but not yet RUNNING.
        self._record_event(spec, "RUNNING" if running else "DISPATCHED",
                           pid=conn.pid)
        return {"t": "task", "spec": spec, "arg_values": arg_values,
                "fn_blob": fn_blob}

    def _dispatch(self, spec: TaskSpec, conn: _WorkerConn):
        t0 = time.time() if self._spec_traced(spec) else 0.0
        conn.send(self._dispatch_msg(spec, conn))
        if t0:
            # dispatch hop: message construction (arg inlining, function
            # blob resolution) + the socket hand-off to the worker
            self._trace_hop(spec, "raylet.dispatch", t0, pid=conn.pid)

    def _dispatch_many(self, specs: List[TaskSpec], conn: _WorkerConn):
        """Dispatch a sequential batch in one coalesced frame; the worker
        sees ordinary per-task messages (recv_msg splits the frames) and
        runs them in order.  current_task ends as specs[0] — the one the
        worker starts executing first."""
        t0 = time.time() if any(map(self._spec_traced, specs)) else 0.0
        msgs = [self._dispatch_msg(s, conn, running=(i == 0))
                for i, s in enumerate(specs)]
        conn.current_task = specs[0]
        try:
            conn.send_many(msgs)
        except OSError:
            # dead pool worker, EOF not yet processed (same race as the
            # actor pump): inflight holds the batch, the death path
            # retries/errors it
            self._on_worker_death(conn)
            return
        if t0:
            for s in specs:
                if self._spec_traced(s):
                    self._trace_hop(s, "raylet.dispatch", t0, pid=conn.pid,
                                    batch=len(specs))

    def _pump_actor(self, actor: _ActorState):
        if actor.node_id is not None and actor.node_id != self.node_id:
            # Remote-executing actor (owner side): relay calls to the exec
            # node; it enforces max_concurrency and FIFO order (TCP keeps
            # our send order).
            if actor.state != "alive":
                return
            while actor.queue:
                spec = actor.queue.popleft()
                if self._dep_errored(spec):
                    continue
                if spec.method_name == "__ray_terminate__":
                    actor.restarts_left = 0
                self._record_event(spec, "FORWARDED", node=actor.node_id)
                if not self._forward_task(spec, actor.node_id):
                    actor.queue.appendleft(spec)
                    return
            return
        def group_of(s: TaskSpec) -> str:
            return getattr(s, "concurrency_group", None) or "_default"

        def group_has_room(s: TaskSpec) -> bool:
            if actor.group_limits is None:
                return True
            g = group_of(s)
            limit = actor.group_limits.get(g,
                                           actor.group_limits["_default"])
            used = sum(1 for f in actor.inflight.values()
                       if group_of(f) == g)
            return used < limit

        # Scan instead of strict FIFO when groups are declared: a task
        # whose group is saturated is skipped so OTHER groups keep flowing
        # (FIFO is preserved WITHIN each group — skipped specs keep their
        # relative order in the deferred queue).
        deferred_groups: deque = deque()
        out_msgs = []
        traced_dispatches: list = []  # (spec, t0, pid) — hop spans
        while (actor.state == "alive" and actor.conn is not None
               and actor.queue and len(actor.inflight) < actor.admit_limit()):
            spec = actor.queue.popleft()
            if self._dep_errored(spec):
                continue
            if self._deadline_expired(spec):
                self._m_deadline_exceeded += 1
                self._shed_spec(spec, DeadlineExceededError(
                    f"call {spec.name} deadline expired in the actor queue",
                    hop="raylet.pre_dispatch"), "EXPIRED", hop="pre_dispatch")
                self._cancel_children(spec.task_id, deadline=True)
                continue
            if not group_has_room(spec):
                deferred_groups.append(spec)
                continue
            if self.cluster_mode and self._remote_deps_pending(spec):
                # A store arg lives on another node: keep FIFO order, park
                # the call until the pull seals it here (waiters fire only
                # on local statuses; duplicates are harmless re-pumps).
                actor.queue.appendleft(spec)
                for oid in spec.dependency_ids():
                    st = self._objects.get(oid)
                    if (st is not None
                            and st.status not in ("inline", "store", "error")):
                        self._object_waiters.setdefault(oid, []).append(
                            lambda _oid, a=actor: self._pump_actor(a))
                break
            if spec.method_name == "__ray_terminate__":
                # Graceful exit: the worker process will exit after replying;
                # the EOF must not be treated as a crash worth restarting.
                actor.restarts_left = 0
            actor.inflight[spec.task_id] = spec
            conn = actor.conn
            conn.state = "busy"
            conn.current_task = spec
            conn.inflight[spec.task_id] = spec
            arg_values = {}
            for oid in spec.dependency_ids():
                st = self._objects.get(oid)
                if st is not None and st.status == "inline":
                    arg_values[oid.hex()] = st.value
            if self._spec_traced(spec):
                traced_dispatches.append((spec, time.time(), conn.pid))
            self._record_event(spec, "RUNNING", pid=conn.pid)
            out_msgs.append({"t": "task", "spec": spec,
                             "arg_values": arg_values, "fn_blob": None})
        if out_msgs and actor.conn is not None:
            # one coalesced frame for the whole pump (one sendall)
            try:
                actor.conn.send_many(out_msgs)
            except OSError:
                # The worker died and a submit raced its EOF onto the dead
                # socket (a direct-channel reconcile can arrive in that
                # window) — the specs are in inflight, so the death path
                # errors/retries them with crash forensics as usual.
                while deferred_groups:
                    actor.queue.appendleft(deferred_groups.pop())
                self._on_worker_death(actor.conn)
                return
            for spec, t0, pid in traced_dispatches:
                self._trace_hop(spec, "raylet.dispatch", t0, pid=pid)
        # put group-saturated specs back at the FRONT, preserving order
        while deferred_groups:
            actor.queue.appendleft(deferred_groups.pop())

    # --------------------------------------------------------------- actors

    def _on_actor_death(self, actor_id: ActorID, reason: str, allow_restart=True):
        actor = self._actors.get(actor_id)
        if actor is None:
            return
        # Direct transport: every death invalidates brokered channels —
        # bump the generation (fences reconciles from the old incarnation)
        # and tell local direct callers to tear down now.
        actor.generation += 1
        actor.direct_info = None
        self._broadcast_direct_fence(actor_ids=[actor_id])
        # release resources held since creation
        self._release_task_resources(actor.creation_spec)
        dead_conn = actor.conn
        if dead_conn is not None:
            dead_conn.actor_id = None
            dead_conn.current_task = None
            dead_conn.inflight.clear()
            actor.conn = None
        interrupted = list(actor.inflight.values())
        actor.inflight.clear()
        if allow_restart and actor.restarts_left != 0:
            if actor.restarts_left > 0:
                actor.restarts_left -= 1
            actor.state = "restarting"
            # interrupted calls fail (max_task_retries=0 semantics)
            err = ActorDiedError(actor_id.hex(), reason + " (restarting)")
            for spec in interrupted:
                if spec.kind == ACTOR_TASK:
                    for oid in spec.return_ids():
                        self._object_error(oid, err)
            # resubmit the creation task on a fresh worker (possibly on a
            # different node — the spill counter restarts with the attempt)
            creation = actor.creation_spec
            creation._acquired_pool = None
            creation._spill_count = 0
            actor.node_id = None
            # Checkpointable actors restart WARM: the creation re-runs
            # __init__ and then __ray_restore__(latest __ray_save__ state)
            # — calls completed after that snapshot are NOT replayed
            # (their side effects since it are lost; callers saw their
            # results and the interrupted tail got a retryable error).
            if actor.checkpoint_oid is not None:
                creation.restore_oid = actor.checkpoint_oid
                self._m_ckpt_restores += 1
            # the restarted worker validates direct hellos against the
            # NEW generation; stale channels/retries fence out
            creation._direct_generation = actor.generation
            if self.cluster_mode and actor.foreign_owner is None:
                self._gcs_post("update_actor", actor_id.binary(),
                               "restarting")
            self._ready_queue.append(creation)
            actor.state = "pending"
            self._schedule()
            return
        actor.state = "dead"
        actor.death_reason = reason
        self._release_actor_checkpoint(actor)
        err = ActorDiedError(actor_id.hex(), reason)
        for spec in interrupted:
            for oid in spec.return_ids():
                self._object_error(oid, err)
        # The creation task's return object lives in conn.inflight (not
        # actor.inflight) while the ACTOR_CREATION_TASK runs — if the worker
        # died mid-creation it would stay pending forever and any get() on
        # the actor-readiness ref would hang.  Error it unless creation
        # already resolved it.
        for oid in actor.creation_spec.return_ids():
            if self._object_status(oid) not in ("inline", "store", "error"):
                self._object_error(oid, err)
        while actor.queue:
            spec = actor.queue.popleft()
            for oid in spec.return_ids():
                self._object_error(oid, err)
        if actor.foreign_owner is not None:
            # exec side of a forwarded actor: the owner runs the restart
            # state machine — report the death there.
            peer = self._get_peer(actor.foreign_owner)
            if peer is not None:
                try:
                    peer.send({"t": "xactor_death", "actor_id": actor_id,
                               "reason": reason})
                except OSError:
                    self._drop_peer(peer)
            del self._actors[actor_id]
        elif actor.name or self.cluster_mode:
            self._gcs_post("remove_actor", actor_id.binary())

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        actor = self._actors.get(actor_id)
        if actor is None:
            if self.cluster_mode:
                # Not ours: relay the kill to the owner.
                owner = self._actor_owner_cache.get(actor_id)
                if owner is None:
                    info = self._gcs_safe(self.gcs.get_actor,
                                          actor_id.binary())
                    owner = info["owner_node"] if info else None
                if owner and owner != self.node_id:
                    peer = self._get_peer(owner)
                    if peer is not None:
                        try:
                            peer.send({"t": "xkill", "actor_id": actor_id,
                                       "no_restart": no_restart})
                        except OSError:
                            self._drop_peer(peer)
            return
        if no_restart:
            actor.restarts_left = 0
        if actor.node_id is not None and actor.node_id != self.node_id:
            # executing on a peer: kill there; death flows back as
            # xactor_death.  Relay no_restart AS GIVEN: the exec side
            # never restarts regardless (foreign actors carry
            # restarts_left=0) but a restart-allowed kill must reach it
            # so a checkpointable actor can take its final snapshot.
            peer = self._get_peer(actor.node_id)
            if peer is not None:
                try:
                    peer.send({"t": "xkill", "actor_id": actor_id,
                               "no_restart": no_restart})
                    return
                except OSError:
                    self._drop_peer(peer)
            # peer unreachable: treat as dead now
            actor.node_id = None
            self._on_actor_death(actor_id, "exec node unreachable",
                                 allow_restart=not no_restart)
            return
        conn = actor.conn
        if conn is not None and conn.pid:
            if (not no_restart
                    and actor.creation_spec.checkpoint_interval > 0):
                # Restart-allowed kill of a checkpointable actor: distinct
                # from hard kill — ask the worker to take a FINAL
                # checkpoint and exit, so the restart restores the exact
                # pre-kill state instead of whatever the last cadence
                # snapshot happened to hold.  (Previously this routed
                # through the same SIGKILL as no_restart=True.)  The
                # request drains behind queued calls, so a wedged or
                # slow actor gets the hard kill after a grace — kill()
                # must never silently become a no-op.
                try:
                    conn.send({"t": "exit_checkpoint"})
                except OSError:
                    pass  # fall through to the hard kill
                else:
                    def force(conn=conn, pid=conn.pid,
                              actor_id=actor.actor_id):
                        live = self._actors.get(actor_id)
                        if live is None or live.conn is not conn:
                            return  # exited gracefully (or restarted)
                        try:
                            os.kill(pid, 9)
                        except OSError:
                            pass

                    self.add_timer(
                        max(0.1, config.kill_checkpoint_grace_s), force)
                    return  # EOF after the final checkpoint drives restart
            try:
                os.kill(conn.pid, 9)
            except OSError:
                pass
        # death will be observed via socket EOF

    # ---------------------------------------- overload / deadlines / cancel

    def _failure_state(self, err) -> str:
        """Task-event state for a worker-reported failure: deadline and
        cancel interruptions enforced ON the worker still show up as
        EXPIRED/CANCELLED events (and count) here, not as generic FAILED."""
        if isinstance(err, DeadlineExceededError):
            self._m_deadline_exceeded += 1
            return "EXPIRED"
        if isinstance(err, TaskCancelledError):
            self._m_cancelled += 1
            return "CANCELLED"
        if isinstance(err, BackPressureError):
            self._m_shed += 1
            return "SHED"
        return "FAILED"

    def _note_child(self, spec: TaskSpec):
        """Record the parent->child cancel fan-out edge (submits made
        while a task ran, relayed or direct).  Bounded LRU on parents."""
        parent = spec.parent_task_id
        if parent is None:
            return
        kids = self._children.get(parent)
        if kids is None:
            kids = self._children[parent] = []
            while len(self._children) > 4096:
                self._children.popitem(last=False)
        if len(kids) < 1024:  # runaway fan-out: stop indexing, not serving
            kids.append(spec.task_id)

    def _deadline_expired(self, spec: TaskSpec) -> bool:
        return (config.deadlines and spec.deadline is not None
                and time.time() > spec.deadline)

    def _shed_spec(self, spec: TaskSpec, err: Exception, state: str,
                   **extra):
        """Terminal rejection of a queued/admitted task: error its
        returns, release anything it pinned, record the task event (a
        shed request still exports its errored span via _record_event)."""
        for oid in spec.return_ids():
            self._object_error(oid, err)
        self._record_event(spec, state, error=self._err_summary(err),
                           **extra)

    def _shed_lowest_headroom(self, queue_, spec: TaskSpec, where: str):
        """Bounded-queue admission (RAY_TPU_MAX_QUEUE_DEPTH): the queue is
        full — shed the task with the LEAST deadline headroom (closest to
        expiry: least likely to finish in time; no deadline = infinite
        headroom), which is the new arrival only when nothing queued is
        worse.  Returns True when the NEW spec was shed (caller must not
        enqueue it)."""
        now = time.time()

        def headroom(s: TaskSpec) -> float:
            return (s.deadline - now) if s.deadline is not None \
                else float("inf")

        victim = spec
        if config.deadlines:
            worst = min(queue_, key=headroom, default=None)
            if worst is not None and headroom(worst) < headroom(victim):
                try:
                    queue_.remove(worst)
                    victim = worst
                except ValueError:  # raced away
                    pass
        self._m_shed += 1
        self._shed_spec(victim, BackPressureError(
            f"{where} at max_queue_depth={config.max_queue_depth}; "
            f"task {victim.name} shed"), "SHED", where=where)
        return victim is spec

    def _on_deadline(self, tid: TaskID, return_oids, name: str):
        """Deadline timer fired: reap the task wherever it still is.
        Queued work is shed here with cancel fan-out to its children;
        running work gets a deadline-flavored cancel frame (the worker's
        own watchdog usually beat us to it — both are idempotent)."""
        if not config.deadlines:
            return
        if all(self._object_status(o) in ("inline", "store", "error")
               for o in return_oids):
            return  # completed (or already errored) in time
        err = DeadlineExceededError(
            f"task {name} missed its deadline", hop="raylet.queue")
        found = self._dequeue_tid(tid)
        if found is not None:
            self._m_deadline_exceeded += 1
            self._shed_spec(found, err, "EXPIRED", hop="queue")
            self._schedule()
        else:
            self._interrupt_running(tid, deadline=True)
        # fan out regardless: downstream work inherited this deadline but
        # its own timers may sit on other nodes' clocks — reap now
        self._cancel_children(tid, deadline=True)

    def _dequeue_tid(self, tid: TaskID) -> Optional[TaskSpec]:
        """Remove a not-yet-running task from whichever queue holds it
        (arg-wait, ready queue, or an actor call queue); returns its spec
        or None."""
        entry = self._waiting.pop(tid, None)
        if entry is not None:
            spec, missing = entry
            for m in missing:
                peers = self._dep_index.get(m)
                if peers:
                    peers.discard(tid)
            return spec
        for spec in self._ready_queue:
            if spec.task_id == tid:
                self._ready_queue.remove(spec)
                return spec
        for actor in self._actors.values():
            for spec in actor.queue:
                if spec.task_id == tid:
                    actor.queue.remove(spec)
                    return spec
        return None

    def _interrupt_running(self, tid: TaskID, deadline: bool) -> bool:
        """Ship a cancel frame to the worker executing ``tid`` (relayed
        dispatch or a direct call we saw a RUNNING note for): its cancel
        registry interrupts the executor thread and the ordinary done
        path reports the typed error."""
        rec = self._direct_running.get(tid)
        conn = rec[0] if rec is not None else None
        if conn is None:
            for c in self._workers.values():
                if tid in c.inflight:
                    conn = c
                    break
        if conn is None:
            return False
        try:
            conn.send({"t": "cancel", "task_id": tid, "deadline": deadline})
        except OSError:
            self._on_worker_death(conn)
            return False
        return True

    def _cancel_children(self, tid: TaskID, deadline: bool = False,
                         _depth: int = 0):
        """Recursive cancel fan-out along recorded parent->child edges."""
        if _depth > 64:
            return
        for child_tid in self._children.pop(tid, ()):
            self._cancel_tid(child_tid, deadline=deadline,
                             recursive=True, _depth=_depth + 1)

    def _note_cancelled(self, tid: TaskID, deadline: bool):
        """Remember a reaped task id so a child whose submit/running note
        is still in flight gets caught at admission (bounded LRU)."""
        self._cancelled_tids[tid] = deadline
        while len(self._cancelled_tids) > 4096:
            self._cancelled_tids.popitem(last=False)

    def _cancelled_flag(self, spec: TaskSpec) -> Optional[bool]:
        """Was this spec — or the parent it was spawned from — already
        reaped by a cancel/deadline fan-out?  Returns the deadline flag
        (False = plain cancel) or None."""
        flag = self._cancelled_tids.get(spec.task_id)
        if flag is None and spec.parent_task_id is not None:
            flag = self._cancelled_tids.get(spec.parent_task_id)
        return flag

    def _cancel_tid(self, tid: TaskID, deadline: bool = False,
                    recursive: bool = True, _depth: int = 0,
                    _relay: bool = True) -> bool:
        """Cancel one task by id wherever it is on this node; optionally
        fan out to its children and relay to peer raylets (forwarded
        tasks / foreign actor calls execute elsewhere)."""
        self._note_cancelled(tid, deadline)
        hit = False
        spec = self._dequeue_tid(tid)
        if spec is not None:
            hit = True
            if deadline:
                self._m_deadline_exceeded += 1
                self._shed_spec(spec, DeadlineExceededError(
                    f"task {spec.name} missed its deadline",
                    hop="raylet.queue"), "EXPIRED", hop="queue")
            else:
                self._m_cancelled += 1
                self._shed_spec(spec, TaskCancelledError(
                    f"task {spec.name} was cancelled before it ran"),
                    "CANCELLED")
            self._schedule()
        elif self._interrupt_running(tid, deadline=deadline):
            # counted when the worker reports the typed error (the done
            # path routes through _failure_state) — counting here too
            # would double every mid-exec cancel
            hit = True
        elif _relay and self.cluster_mode:
            # not here: the task may have been forwarded / executed on
            # a peer (foreign actor call, spillback) — one-hop relay
            for peer in list(self._peers.values()):
                try:
                    peer.send({"t": "xcancel", "task_id": tid,
                               "deadline": deadline,
                               "recursive": recursive})
                except OSError:
                    self._drop_peer(peer)
        if recursive:
            self._cancel_children(tid, deadline=deadline, _depth=_depth)
        return hit

    def cancel_task(self, oid: ObjectID, force: bool = False,
                    recursive: bool = True) -> bool:
        """Cancel the task that produces ``oid`` (reference:
        ``CoreWorker::CancelTask``): queued work is dropped with a typed
        ``TaskCancelledError``, RUNNING work is interrupted in its
        executor thread, and ``recursive=True`` fans the cancel out to
        every task it spawned (``force`` currently behaves like a normal
        cancel — the interrupt already stops execution)."""
        return self._cancel_tid(oid.task_id(), deadline=False,
                                recursive=recursive)

    # --------------------------------------------------------------- requests

    def _handle_request(self, conn: Optional[_WorkerConn], msg: dict):
        """Requests from workers (over socket).  Driver uses direct calls."""
        rid = msg["rid"]
        op = msg["op"]

        def reply(ok=True, value=None, error=None):
            # _queue_reply coalesces every reply generated by one drained
            # train into a single sendall per conn.
            self._queue_reply(conn, {"t": "reply", "rid": rid, "ok": ok,
                                     "value": value, "error": error})

        def deferred_reply(value):
            # A worker that timed out already popped its pending entry, so a
            # late reply is simply ignored on its side; a dead socket is
            # swallowed here.
            conn.request_cancels.pop(rid, None)
            try:
                self._queue_reply(conn, {"t": "reply", "rid": rid,
                                         "ok": True, "value": value})
            except OSError:
                pass

        try:
            if op == "get":
                ids = [ObjectID.from_hex(h) for h in msg["ids"]]
                cancel = self.async_get(ids, deferred_reply)
                if cancel is not None:
                    conn.request_cancels[rid] = cancel
            elif op == "wait":
                ids = [ObjectID.from_hex(h) for h in msg["ids"]]
                cancel = self.async_wait(
                    ids, msg["num_returns"], msg.get("timeout"), deferred_reply,
                )
                if cancel is not None:
                    conn.request_cancels[rid] = cancel
            elif op == "put_inline":
                self._object_inline(ObjectID.from_hex(msg["id"]), msg["blob"],
                                    contains=msg.get("contains"))
                reply()
            elif op == "register_stored":
                oid = ObjectID.from_hex(msg["id"])
                if "size" in msg:
                    self._obj(oid).size = msg["size"]
                self._object_in_store(oid, contains=msg.get("contains"))
                self._maybe_replicate(oid,
                                      force=msg.get("replicate", False))
                reply()
            elif op == "kv_put":
                self.gcs.kv_put(msg["ns"], msg["key"], msg["val"])
                reply()
            elif op == "kv_get":
                reply(value=self.gcs.kv_get(msg["ns"], msg["key"]))
            elif op == "kv_del":
                reply(value=self.gcs.kv_del(msg["ns"], msg["key"]))
            elif op == "kv_keys":
                reply(value=self.gcs.kv_keys(msg["ns"], msg["prefix"]))
            elif op == "put_function":
                self._fn_cache[msg["id"]] = msg["blob"]
                self.gcs.put_function(msg["id"], msg["blob"])
                reply()
            elif op == "get_function":
                blob = self._fn_cache.get(msg["id"])
                if blob is None:
                    blob = self.gcs.get_function(msg["id"])
                reply(value=blob)
            elif op == "named_actor":
                info = self.gcs.lookup_named_actor(
                    msg.get("namespace", ""), msg["name"])
                if info is None:
                    reply(ok=False, error=ValueError(
                        f"no actor named {msg['name']!r}"))
                elif info.get("state") == "dead":
                    reply(ok=False, error=ActorDiedError(
                        info["actor_id"].hex(),
                        info.get("death_reason", "actor is dead")))
                else:
                    import cloudpickle as _cp

                    spec = (_cp.loads(info["spec_blob"])
                            if info.get("spec_blob") else None)
                    if spec is None:
                        aid = ActorID(info["actor_id"])
                        local = self._actors.get(aid)
                        spec = local.creation_spec if local else None
                    reply(value={
                        "actor_id": ActorID(info["actor_id"]),
                        "creation_spec": spec,
                    })
            elif op == "actor_state":
                actor = self._actors.get(msg["actor_id"])
                if actor is not None:
                    reply(value=actor.state)
                else:
                    info = (self._gcs_safe(self.gcs.get_actor,
                                           msg["actor_id"].binary())
                            if self.cluster_mode else None)
                    reply(value=info["state"] if info else None)
            elif op == "free":
                for h in msg["ids"]:
                    self.drop_object(ObjectID.from_hex(h))
                reply()
            elif op == "stream_next":
                cancel = self.async_stream_next(
                    msg["task_id"], msg["index"], deferred_reply)
                if cancel is not None:
                    conn.request_cancels[rid] = cancel
            elif op == "reconstruct":
                reply(value=self.reconstruct_object(
                    ObjectID.from_hex(msg["id"])))
            elif op == "cancel_task":
                reply(value=self.cancel_task(
                    ObjectID.from_hex(msg["id"]),
                    force=msg.get("force", False),
                    recursive=msg.get("recursive", True)))
            elif op == "available_resources":
                reply(value=dict(self.resources_available))
            elif op == "cluster_resources":
                reply(value=dict(self.resources_total))
            elif op == "nodes":
                reply(value=self.gcs.nodes())
            elif op == "gcs_list_actors":
                reply(value=self.gcs.list_actors())
            elif op == "cancel_request":
                # The worker timed out and dropped its pending entry:
                # deregister the waiters so they don't accumulate on the
                # object for its whole lifetime.
                cancel = conn.request_cancels.pop(msg["target_rid"], None)
                if cancel is not None:
                    self._safe(cancel)
                reply()
            elif op == "pg_state":
                reply(value=self.pg_state(msg["pg_id"]))
            elif op == "create_pg":
                ok = self.create_pg(
                    msg["pg_id"], msg["bundles"], msg["strategy"],
                    ready_oid=msg.get("ready_oid"),
                )
                reply(value=ok)
            elif op == "remove_pg":
                self.remove_pg(msg["pg_id"])
                reply()
            elif op == "state_snapshot":
                reply(value=self.state_snapshot(
                    objects_limit=msg.get("objects_limit", 0)))
            elif op == "flush_task_events":
                self.flush_task_events()
                reply()
            elif op in ("list_task_events", "summarize_task_events",
                        "task_events_raw"):
                # Cluster-wide state reads proxied to the GCS task-event
                # table; flush first so this node's freshest events count.
                self.flush_task_events()
                kw = {k: msg[k] for k in ("job_id", "state", "limit")
                      if k in msg}
                reply(value=self._gcs_safe(getattr(self.gcs, op), **kw))
            elif op == "flush_trace_spans":
                self.flush_trace_spans()
                reply()
            elif op in ("get_trace", "list_trace_spans",
                        "trace_table_stats"):
                # Cluster-wide trace reads proxied to the GCS trace table;
                # flush so this node's freshest spans count.
                self.flush_trace_spans()
                kw = {k: msg[k] for k in ("trace_id", "job_id", "limit")
                      if k in msg}
                reply(value=self._gcs_safe(getattr(self.gcs, op), **kw))
            elif op == "flush_profile_samples":
                self.flush_profile_samples()
                reply()
            elif op in ("list_profile_samples", "profile_table_stats"):
                # Cluster-wide profile reads proxied to the GCS profile
                # table; flush so this node's freshest window counts.
                self.flush_profile_samples()
                kw = {k: msg[k] for k in ("node_id", "since", "limit")
                      if k in msg}
                reply(value=self._gcs_safe(getattr(self.gcs, op), **kw))
            elif op == "flush_metric_points":
                self.flush_metric_points()
                reply()
            elif op in ("query_metrics", "metrics_table_stats"):
                # Cluster-wide time-series reads proxied to the GCS
                # metrics table; flush so this node's freshest deltas
                # count (other nodes' points land on their own 1s ticks).
                self.flush_metric_points()
                kw = {k: msg[k] for k in ("name", "query_op", "tags",
                                          "node_id", "since", "until",
                                          "window_s", "q", "limit")
                      if k in msg}
                if "query_op" in kw:
                    kw["op"] = kw.pop("query_op")
                reply(value=self._gcs_safe(getattr(self.gcs, op), **kw))
            elif op == "list_alerts":
                kw = {k: msg[k] for k in ("state", "limit") if k in msg}
                reply(value=self._gcs_safe(self.gcs.list_alerts, **kw))
            elif op == "dump_stacks":
                # this node only: raylet process + all local workers
                self.collect_local_stacks(deferred_reply,
                                          pid=msg.get("pid"))
            elif op == "collect_stacks":
                # cluster-wide: the blocking GCS gather runs off-thread —
                # the event thread must stay free to answer OUR share
                self._spawn_gcs_query(
                    deferred_reply, "collect_stacks",
                    node_id=msg.get("node_id"), pid=msg.get("pid"),
                    timeout_s=msg.get("timeout_s", 3.0))
            elif op == "gcs_node_query":
                self._spawn_gcs_query(
                    deferred_reply, "node_query",
                    node_id=msg.get("node_id"), kind=msg["kind"],
                    payload=msg.get("payload"),
                    timeout_s=msg.get("timeout_s", 3.0))
            elif op == "list_logs":
                reply(value=self._list_logs())
            elif op == "tail_log":
                reply(value=self._tail_log(msg.get("name"),
                                           msg.get("offset"),
                                           msg.get("lines", 100)))
            elif op == "kill_actor":
                self.kill_actor(msg["actor_id"], msg.get("no_restart", True))
                reply()
            elif op == "direct_lookup":
                # direct-transport broker: the requester becomes a fence
                # subscriber (actor-death / node-SUSPECT teardown notices)
                conn.uses_direct = True
                reply(value=self.direct_call_info(msg["actor_id"]))
            elif op == "direct_lease":
                conn.uses_direct = True
                reply(value=self.acquire_direct_lease(msg["spec"]))
            elif op == "direct_lease_release":
                self.release_direct_lease(msg["lease_id"])
                reply()
            else:
                reply(ok=False, error=ValueError(f"unknown op {op}"))
        except Exception as e:  # noqa: BLE001
            try:
                reply(ok=False, error=e)
            except OSError:
                pass

    # get/wait used by both driver (via call) and workers (via requests).

    def _remove_waiter(self, oid: ObjectID, cb: Callable):
        lst = self._object_waiters.get(oid)
        if lst is not None:
            try:
                lst.remove(cb)
            except ValueError:
                pass
            if not lst:
                del self._object_waiters[oid]

    def async_get(self, ids: List[ObjectID], done_cb: Callable[[dict], None]):
        """done_cb receives {hex: ("inline", bytes) | ("store",) | ("error", e)}.

        Returns a cancel callable (or None if done synchronously) that
        deregisters the pending waiters — callers that time out MUST invoke
        it or the waiter list grows for the object's lifetime.
        """
        remaining = set()
        results: Dict[str, tuple] = {}

        def check(oid: ObjectID):
            st = self._objects.get(oid)
            status = st.status if st else "pending"
            if status == "inline":
                results[oid.hex()] = ("inline", st.value)
            elif status == "store":
                results[oid.hex()] = ("store",)
            elif status == "error":
                results[oid.hex()] = ("error", st.error)
            else:
                if self.cluster_mode:
                    # sealed elsewhere (or unknown): fetch it here; the
                    # waiter resolves on local seal
                    self._maybe_pull(oid)
                return False
            return True

        def on_ready(oid: ObjectID):
            if oid in remaining and check(oid):
                remaining.discard(oid)
                if not remaining:
                    done_cb(results)

        for oid in ids:
            if not check(oid):
                remaining.add(oid)
        if not remaining:
            done_cb(results)
            return None
        for oid in list(remaining):
            self._object_waiters.setdefault(oid, []).append(on_ready)

        def cancel():
            for oid in list(remaining):
                self._remove_waiter(oid, on_ready)
            remaining.clear()

        return cancel

    def async_wait(self, ids: List[ObjectID], num_returns: int,
                   timeout: Optional[float], done_cb: Callable[[List[str]], None]):
        """Returns a cancel callable (or None if done synchronously)."""
        # Dedup: the same callback registered once per duplicate id would
        # count a single object's readiness multiple times toward
        # num_returns (reference rejects duplicate refs in ray.wait).
        ids = list(dict.fromkeys(ids))
        num_returns = min(num_returns, len(ids))
        ready: List[str] = []
        fired = [False]
        pending: List[ObjectID] = []

        def is_ready(oid):
            status = self._object_status(oid)
            if status == "remote" and self.cluster_mode:
                self._maybe_pull(oid)  # fetch_local semantics
            return status in ("inline", "store", "error")

        def cleanup():
            for oid in pending:
                self._remove_waiter(oid, on_ready)
            pending.clear()

        def reply_value():
            # errored subset rides along: wait() counts an error as ready
            # (ray semantics), but the direct transport's engagement
            # watermark must not clear on one — a raylet-side failure
            # (dep error, dead actor) proves nothing about delivery of
            # the calls before it.
            return {"ready": ready,
                    "errored": [h for h in ready
                                if self._object_status(
                                    ObjectID.from_hex(h)) == "error"]}

        def fire():
            if not fired[0]:
                fired[0] = True
                cleanup()
                done_cb(reply_value())

        def on_ready(oid: ObjectID):
            if fired[0]:
                return
            ready.append(oid.hex())
            if len(ready) >= num_returns:
                fire()

        for oid in ids:
            if is_ready(oid):
                ready.append(oid.hex())
        if len(ready) >= num_returns:
            ready[:] = ready[:num_returns]
            fired[0] = True
            done_cb(reply_value())
            return None

        pending.extend(oid for oid in ids if not is_ready(oid))
        for oid in pending:
            self._object_waiters.setdefault(oid, []).append(on_ready)
        if timeout is not None:
            self.add_timer(timeout, fire)

        def cancel():
            fired[0] = True
            cleanup()

        return cancel

    # --------------------------------------------------------------- PGs

    def create_pg(self, pg_id: str, bundles: List[Dict[str, float]],
                  strategy: str, ready_oid: Optional[ObjectID] = None) -> bool:
        if self.cluster_mode:
            # GCS places bundles across nodes and pushes pg_reserve to the
            # involved raylets; ready resolves on the pg_ready push.
            # Transient GCS failures RAISE (propagating to the caller)
            # rather than masquerading as "exceeds capacity".
            ok = self.gcs.create_pg(pg_id, bundles, strategy, self.node_id)
            if not ok:
                return False
            if ready_oid is not None:
                self._obj(ready_oid)
            self._cluster_pg_ready[pg_id] = ready_oid
            return True
        pg = _PlacementGroup(pg_id, bundles, strategy, ready_oid=ready_oid)
        total = pg.total()
        if not _fits(self.resources_total, total):
            # Exceeds total node capacity: can never be satisfied (the
            # multi-node scheduler will spread bundles across nodes instead).
            return False
        if ready_oid is not None:
            self._obj(ready_oid)
        self._pgs[pg_id] = pg
        if _fits(self.resources_available, total):
            _acquire(self.resources_available, total)
            pg.unreserved.clear()
            pg.state = "created"
            if ready_oid is not None:
                self._object_inline(ready_oid, _PG_READY_BLOB)
        # else: stays pending; _activate_pending_pgs reserves it when
        # resources free up (reference queues infeasible PGs — never drives
        # availability negative).
        return True

    def pg_state(self, pg_id: str) -> Optional[str]:
        pg = self._pgs.get(pg_id)
        if pg is not None and not pg.fragment:
            return pg.state
        if self.cluster_mode:
            info = self._gcs_safe(self.gcs.pg_info, pg_id)
            if info is not None:
                return info["state"] if info["state"] == "created" \
                    else "pending"
        return pg.state if pg is not None else None

    def remove_pg(self, pg_id: str, _from_gcs: bool = False):
        if self.cluster_mode and not _from_gcs:
            # cluster PG: the GCS fans pg_remove out to every fragment
            # holder (including us); local cleanup happens on that push
            if self._gcs_safe(self.gcs.remove_cluster_pg, pg_id):
                return
        pg = self._pgs.pop(pg_id, None)
        if pg is None:
            return
        removed_err = ValueError(f"placement group {pg_id} was removed")
        # Tasks targeting this PG could never schedule again — fail them
        # now instead of deferring forever.  Both the ready queue and the
        # dep-blocked table must be purged: a waiting task would re-enter
        # the ready queue after this purge and then defer on every
        # _schedule pass.
        # Collect victims first: _object_error re-enters _schedule, which
        # mutates the ready queue — never error while iterating it.
        victims = [s for s in self._ready_queue
                   if (s.placement or {}).get("pg") == pg_id]
        self._ready_queue = deque(
            s for s in self._ready_queue
            if (s.placement or {}).get("pg") != pg_id)
        for task_id, (spec, missing) in list(self._waiting.items()):
            if (spec.placement or {}).get("pg") != pg_id:
                continue
            del self._waiting[task_id]
            for m in missing:
                peers = self._dep_index.get(m)
                if peers:
                    peers.discard(task_id)
            victims.append(spec)
        for spec in victims:
            for oid in spec.return_ids():
                self._object_error(oid, removed_err)
            self._record_event(spec, "FAILED", pg_removed=True)
        if pg.state == "created":
            # Reference kills PG-leased workers on removal
            # (`gcs_placement_group_scheduler.cc` destroys bundle leases):
            # reclaim actors and running tasks inside the bundles before
            # returning capacity so the node pool isn't oversubscribed by
            # processes still running in the removed group.
            for actor in list(self._actors.values()):
                if ((actor.creation_spec.placement or {}).get("pg") != pg_id
                        or actor.state == "dead"):
                    continue
                if actor.conn is None:
                    # Not yet dispatched (pending/restarting): there is no
                    # process to kill and no EOF will ever arrive — mark it
                    # dead directly or it hangs in state "pending" forever.
                    actor.restarts_left = 0
                    self._on_actor_death(actor.actor_id, "placement group "
                                         "removed", allow_restart=False)
                else:
                    self.kill_actor(actor.actor_id)
            for conn in list(self._workers.values()):
                if conn.actor_id is not None:
                    continue
                for spec in conn.inflight.values():
                    if (spec.placement or {}).get("pg") == pg_id:
                        spec.retries_left = 0
                        if conn.pid:
                            try:
                                os.kill(conn.pid, 9)
                            except OSError:
                                pass
                        break
            _release(self.resources_available, pg.reserved_total())
        else:
            # pending: a FRAGMENT may hold per-bundle partial reservations
            _release(self.resources_available, pg.reserved_total())
            if pg.ready_oid is not None:
                # never becomes ready: fail its ready() object so waiters
                # unblock instead of hanging forever
                self._object_error(pg.ready_oid, ValueError(
                    f"placement group {pg_id} was removed before its "
                    "bundles could be reserved"))
        self._schedule()

    # --------------------------------------------------------------- state

    @staticmethod
    def _err_summary(err) -> str:
        try:
            first = str(err).strip().splitlines()
            return f"{type(err).__name__}: {first[0] if first else ''}"[:200]
        except Exception:  # noqa: BLE001
            return type(err).__name__

    # ---- request-flow tracing (hop spans + span export pipeline) ----

    # Lifecycle interval -> hop span emitted when the NEXT transition
    # closes it.  RUNNING is deliberately absent: the executing worker's
    # task.run span (with get_args/exec/result_push children) owns that
    # interval — a raylet-side copy would double-attribute it.
    _TRACE_PHASE = {
        "PENDING_ARGS": "raylet.pending_args",
        "QUEUED": "raylet.queue",
        "FORWARDED": "raylet.await_remote",
        "SPILLED": "raylet.await_remote",
        "RECONSTRUCTING": "raylet.reconstructing",
    }

    @staticmethod
    def _spec_traced(spec: TaskSpec) -> bool:
        """Does this spec belong to a SAMPLED trace?  (The ctx rides the
        spec across processes; unsampled requests carry the bit so error
        paths can still export with real ids.)"""
        ctx = spec.trace_ctx
        return ctx is not None and ctx.get("sampled", True) \
            and _tracing.tracing_enabled()

    def _trace_hop(self, spec: TaskSpec, name: str, t0: float,
                   t1: Optional[float] = None, status: str = "OK",
                   error: Optional[str] = None, **attrs):
        """Emit one measured hop span under the request's submit span."""
        ctx = spec.trace_ctx
        _tracing.emit_span(
            f"{name} {spec.name}", ctx["trace_id"], ctx.get("span_id"),
            t0, time.time() if t1 is None else t1, status=status,
            error=error, proc="raylet", task_id=spec.task_id.hex(), **attrs)
        self._arm_trace_flush()

    def _arm_trace_flush(self):
        """Schedule a span flush for locally-emitted spans (they land in
        the process buffer without a control frame to piggyback on)."""
        if not self._trace_timer_armed:
            self._trace_timer_armed = True
            self.add_timer(config.trace_flush_interval_s,
                           self._trace_flush_tick)

    def _trace_transition(self, spec: TaskSpec, state: str, t: float,
                          error: Optional[str] = None):
        """Lifecycle transition -> close the previous phase's interval as
        a hop span.  The first transition also closes the inbox interval
        (raylet receipt -> first classification) opened by submit_task."""
        prev = getattr(spec, "_tr_prev", None)
        if prev is None:
            t_in = getattr(spec, "_tr_in", None)
            if t_in is not None:
                self._trace_hop(spec, "raylet.inbox", t_in, t)
        else:
            name = self._TRACE_PHASE.get(prev[0])
            if name is not None:
                failed = state == "FAILED"
                self._trace_hop(spec, name, prev[1], t,
                                status="ERROR" if failed else "OK",
                                error=error if failed else None)
        spec._tr_prev = (state, t)

    def _trace_ingest(self, spans: List[dict], dropped: int = 0):
        """Append a span batch (worker control frames / the local
        process buffer) to the bounded export buffer and arm the flush."""
        buf = self._trace_buf
        cap = config.trace_buffer_size
        self._trace_export_dropped += dropped
        self._trace_dropped_total += dropped
        for sp in spans:
            buf.append(sp)
            if len(buf) > cap:
                buf.popleft()
                self._trace_export_dropped += 1
                self._trace_dropped_total += 1
        if buf:
            self._arm_trace_flush()

    def flush_trace_spans(self):
        """Drain this process's span buffer plus everything workers have
        shipped, and post the batch to the GCS trace table."""
        local, dropped = _tracing.drain_pending()
        if local or dropped:
            self._trace_ingest(local, dropped)
        if not self._trace_buf and not self._trace_export_dropped:
            return
        t0 = time.perf_counter()
        spans = list(self._trace_buf)
        self._trace_buf.clear()
        dropped = self._trace_export_dropped
        self._trace_export_dropped = 0
        try:
            if isinstance(self.gcs, GcsClient):
                self.gcs.post("add_trace_spans", self.node_id, spans,
                              dropped, incarnation=self.incarnation)
            else:
                self.gcs.add_trace_spans(self.node_id, spans, dropped,
                                         incarnation=self.incarnation)
        except (ConnectionError, TimeoutError, OSError):
            # GCS unreachable: the batch is gone — count it (locally for
            # the metric, and toward the next successful flush so
            # trace_table_stats sees the hole) instead of silently
            # reporting zero drops across an outage.
            self._trace_dropped_total += len(spans)
            self._trace_export_dropped += dropped + len(spans)
        self._audit_flush("trace", t0, batch=spans)

    def _trace_flush_tick(self):
        # One-shot timer, armed lazily by the first ingest: an untraced
        # raylet pays nothing for the span pipeline.
        self._trace_timer_armed = False
        self.flush_trace_spans()
        # The driver emits spans without notifying the raylet (same
        # process, different thread): while tracing is live, keep a slow
        # heartbeat so a trailing driver-only span (a late task.get, a
        # serve.route) can't strand in the process buffer forever.
        if not self._shutdown and (_tracing.tracing_enabled()
                                   or _tracing.has_pending()):
            self._trace_timer_armed = True
            self.add_timer(config.trace_flush_interval_s,
                           self._trace_flush_tick)

    # ---- continuous profiling (folded stack samples -> GCS table) ----

    def _profile_ingest(self, samples: List[dict], dropped: int = 0):
        """Append a folded-sample batch (worker control frames / the
        local sampler) to the bounded export buffer."""
        buf = self._profile_buf
        cap = config.profile_buffer_size
        self._profile_export_dropped += dropped
        self._profile_dropped_total += dropped
        for rec in samples:
            buf.append(rec)
            if len(buf) > cap:
                buf.popleft()
                self._profile_export_dropped += 1
                self._profile_dropped_total += 1

    def flush_profile_samples(self):
        """Drain this process's sampler window plus everything workers
        have shipped, and post the batch to the GCS profile table."""
        local, dropped = _profiling.drain_samples()
        if local or dropped:
            self._profile_ingest(local, dropped)
        if not self._profile_buf and not self._profile_export_dropped:
            return
        t0 = time.perf_counter()
        samples = list(self._profile_buf)
        self._profile_buf.clear()
        dropped = self._profile_export_dropped
        self._profile_export_dropped = 0
        try:
            if isinstance(self.gcs, GcsClient):
                self.gcs.post("add_profile_samples", self.node_id, samples,
                              dropped, incarnation=self.incarnation)
            else:
                self.gcs.add_profile_samples(self.node_id, samples, dropped,
                                             incarnation=self.incarnation)
        except (ConnectionError, TimeoutError, OSError):
            # GCS unreachable: the batch is gone — count it honestly
            self._profile_dropped_total += len(samples)
            self._profile_export_dropped += dropped + len(samples)
        self._audit_flush("profile", t0, batch=samples)

    def _profile_flush_tick(self):
        # Recurring (unlike the lazily-armed trace timer): samples
        # originate on the sampler thread, which can't arm event-thread
        # timers — with profiling off this is one empty-buffer check per
        # interval.
        if self._shutdown:
            return
        self.flush_profile_samples()
        self.add_timer(config.profile_flush_interval_s,
                       self._profile_flush_tick)

    # ---- metric time-series export (delta points -> GCS table) ----

    def _metric_points_ingest(self, points: List[dict], dropped: int = 0):
        """Append a delta-point batch (worker control frames / the local
        registry ring / the raylet's own internal set) to the bounded
        export buffer."""
        buf = self._metric_point_buf
        cap = config.metrics_history_ring
        self._metric_points_export_dropped += dropped
        self._metric_points_dropped_total += dropped
        for p in points:
            buf.append(p)
            if len(buf) > cap:
                buf.popleft()
                self._metric_points_export_dropped += 1
                self._metric_points_dropped_total += 1

    def flush_metric_points(self):
        """Drain this process's point ring plus everything workers have
        shipped, and post the batch to the GCS metrics table."""
        local, dropped = _metrics_mod.drain_points()
        if local or dropped:
            self._metric_points_ingest(local, dropped)
        if not self._metric_point_buf and \
                not self._metric_points_export_dropped:
            return
        t0 = time.perf_counter()
        points = list(self._metric_point_buf)
        self._metric_point_buf.clear()
        dropped = self._metric_points_export_dropped
        self._metric_points_export_dropped = 0
        try:
            if isinstance(self.gcs, GcsClient):
                self.gcs.post("add_metric_points", self.node_id, points,
                              dropped, incarnation=self.incarnation)
            else:
                self.gcs.add_metric_points(self.node_id, points, dropped,
                                           incarnation=self.incarnation)
        except (ConnectionError, TimeoutError, OSError):
            # GCS unreachable: the batch is gone — count it honestly
            self._metric_points_dropped_total += len(points)
            self._metric_points_export_dropped += dropped + len(points)
        self._audit_flush("metrics", t0, batch=points)

    def _audit_flush(self, subsystem: str, t0: float,
                     batch: Optional[list] = None, nbytes: float = 0.0):
        """Telemetry self-audit: accumulate wall time and approximate
        shipped bytes per export subsystem (task_events / trace / profile
        / metrics), re-exported as ray_tpu_internal_telemetry_flush_*
        counters.  Dict batches are costed as records x one sampled
        record's JSON size — serializing the whole batch just to weigh it
        would double the very cost being measured."""
        import json as _json

        slot = self._m_telemetry.get(subsystem)
        if slot is None:
            slot = self._m_telemetry[subsystem] = [0.0, 0.0]
        slot[0] += time.perf_counter() - t0
        if batch:
            try:
                rec = len(_json.dumps(batch[0], default=str))
            except (TypeError, ValueError):
                rec = 0
            nbytes += rec * len(batch)
        slot[1] += nbytes

    # ---- live introspection (stack dumps / targeted node queries) ----

    def collect_local_stacks(self, done_cb: Callable[[List[dict]], None],
                             pid: Optional[int] = None,
                             timeout_s: float = 1.5):
        """Gather all-thread stacks from this process and every
        registered worker (the ``ray stack`` payload).  Workers answer
        from their socket-reader threads, so a worker stuck in user code
        (or deadlocked) still reports.  ``done_cb(procs)`` fires on the
        event thread — with whatever arrived by ``timeout_s`` if some
        worker never answers."""
        own_label = "raylet" if self.cluster_mode else "driver"
        procs: List[dict] = []
        if pid is None or pid == os.getpid():
            procs.append({"pid": os.getpid(), "proc": own_label,
                          "node_id": self.node_id,
                          "threads": _profiling.dump_threads(
                              proc=own_label)})
        targets = [c for c in self._workers.values()
                   if c.pid is not None
                   and getattr(c, "state", None) != "driver"
                   and (pid is None or c.pid == pid)]
        if not targets:
            done_cb(procs)
            return
        token = f"s{next(self._stack_token_seq)}"
        state = {"want": len(targets), "procs": procs, "cb": done_cb,
                 "done": False}
        self._stack_queries[token] = state
        for c in targets:
            try:
                c.send({"t": "stack", "token": token})
            except OSError:
                state["want"] -= 1
        if state["want"] <= 0:
            self._stack_queries.pop(token, None)
            done_cb(procs)
            return

        def deadline(token=token):
            st = self._stack_queries.pop(token, None)
            if st is not None and not st["done"]:
                st["done"] = True
                st["cb"](st["procs"])

        self.add_timer(max(0.2, timeout_s), deadline)

    def _on_stack_reply(self, conn: _WorkerConn, msg: dict):
        st = self._stack_queries.get(msg.get("token"))
        if st is None or st["done"]:
            return  # deadline already fired (late reply) — drop it
        st["procs"].append({"pid": msg.get("pid") or conn.pid,
                            "proc": "worker", "node_id": self.node_id,
                            "actor_id": (conn.actor_id.hex()
                                         if conn.actor_id else None),
                            "threads": msg.get("threads") or []})
        st["want"] -= 1
        if st["want"] <= 0:
            st["done"] = True
            self._stack_queries.pop(msg.get("token"), None)
            st["cb"](st["procs"])

    def _handle_node_query(self, data: dict):
        """A targeted GCS introspection push (``node_query``): collect the
        answer locally and post it back as a one-way report."""
        kind, token = data.get("kind"), data.get("token")
        payload = data.get("payload") or {}
        if kind == "stacks":
            self.collect_local_stacks(
                lambda procs: self._gcs_post(
                    "node_query_report", token, self.node_id, procs),
                pid=payload.get("pid"))
        elif kind == "logs":
            try:
                value = self._logs_query(payload)
            except (OSError, ValueError) as e:
                value = {"error": repr(e)}
            self._gcs_post("node_query_report", token, self.node_id, value)
        elif kind == "profile_flush":
            self.flush_profile_samples()
            self._gcs_post("node_query_report", token, self.node_id, True)
        # unknown kinds: no report — the requester lists this node missing

    def _spawn_gcs_query(self, deferred_reply: Callable, op: str, **kw):
        """Run a BLOCKING cluster-wide GCS gather (collect_stacks /
        node_query) on a throwaway thread and reply when it returns — the
        event thread must stay free to answer this node's own share of
        the query (the GCS pushes it right back at us)."""
        def run():
            try:
                value = getattr(self.gcs, op)(**kw)
            except Exception as e:  # noqa: BLE001 — reply, don't die
                value = {"reports": {}, "nodes": {}, "missing": [],
                         "error": repr(e)}
            self.call_async(deferred_reply, value)

        threading.Thread(target=run, name=f"gcs-query-{op}",
                         daemon=True).start()

    def _record_event(self, spec: TaskSpec, state: str, **extra):
        attempt = spec.max_retries - spec.retries_left
        ev = {
            "task_id": spec.task_id.hex(),
            "name": spec.name,
            "kind": spec.kind,
            "state": state,
            "time": time.time(),
            "node_id": self.node_id,
            "job_id": spec.job_id,
            "attempt": attempt if attempt > 0 else 0,
            **extra,
        }
        if spec.trace_ctx is not None and _tracing.tracing_enabled():
            if spec.trace_ctx.get("sampled", True):
                # task events <-> traces: a slow row in summarize_tasks /
                # timeline() jumps straight to its waterfall
                ev["trace_id"] = spec.trace_ctx["trace_id"]
                self._trace_transition(spec, state, ev["time"],
                                       error=extra.get("error"))
            elif state in ("FAILED", "SHED", "EXPIRED", "CANCELLED"):
                # head-sampled out, but errored requests always export —
                # a shed/expired request still shows up as an ERROR span
                self._trace_hop(spec, f"raylet.task_{state.lower()}",
                                ev["time"], ev["time"], status="ERROR",
                                error=extra.get("error"))
        self._task_events.append(ev)
        states = self._task_states
        # pop+reinsert: dict order becomes least-recently-UPDATED first, so
        # the overflow eviction below drops stale finished tasks before a
        # long-running task that just reported RUNNING
        states.pop(spec.task_id, None)
        states[spec.task_id] = ev
        if len(states) > self._flag_state_cap.value:
            # bound the per-task state map like the event deque: a driver
            # submitting forever must not grow raylet memory without limit
            states.pop(next(iter(states)))
        if state in ("RUNNING", "DISPATCHED"):
            queued_t = getattr(spec, "_queued_t", None)
            if queued_t is not None and self._im is not None:
                spec._queued_t = None
                self._im["dispatch_latency"].observe(
                    time.monotonic() - queued_t)
        elif state in ("FINISHED", "FAILED", "SHED", "EXPIRED", "CANCELLED"):
            self._m_tasks_done[state] += 1
        # ---- export to the GCS task-event table ----
        if not self._flag_task_events.value:
            return
        buf = self._task_event_buf
        buf.append(ev)
        if len(buf) > self._flag_event_cap.value:
            buf.popleft()
            self._task_event_dropped += 1
            self._task_event_dropped_total += 1
        if not self._task_event_timer_armed:
            self._task_event_timer_armed = True
            self.add_timer(config.task_event_flush_interval_s,
                           self._task_event_flush_tick)

    def flush_task_events(self):
        """Ship the export ring buffer to the GCS task-event table (one
        one-way post; event thread only).  Driver/state-API callers invoke
        this before querying so a just-finished task is visible."""
        if not self._task_event_buf and not self._task_event_dropped:
            return
        t0 = time.perf_counter()
        events = list(self._task_event_buf)
        self._task_event_buf.clear()
        dropped, self._task_event_dropped = self._task_event_dropped, 0
        self._gcs_post("add_task_events", self.node_id, events, dropped,
                       incarnation=self.incarnation)
        self._audit_flush("task_events", t0, batch=events)

    def _task_event_flush_tick(self):
        # One-shot timer, re-armed lazily by the next _record_event: an
        # idle raylet pays nothing for the export pipeline.
        self._task_event_timer_armed = False
        self.flush_task_events()

    # ---- internal runtime metrics (ray_tpu_internal_*) ----

    def _init_internal_metrics(self):
        """Instrument the runtime with the util.metrics primitives under
        the reserved prefix (reference: the ray_* internal gauges exported
        by the per-node metrics agent, `metrics_agent.py:375`).  The raylet
        flushes these itself through the GCS KV metrics namespace — raylet
        processes have no global worker for the per-process flusher."""
        from ray_tpu.util import metrics as _metrics

        tags = {"node": self.node_id[:12]}

        def gauge(name, desc):
            return _metrics.internal_metric(
                _metrics.Gauge, name, desc,
                tag_keys=("node",)).set_default_tags(tags)

        def counter(name, desc, tag_keys=("node",)):
            return _metrics.internal_metric(
                _metrics.Counter, name, desc,
                tag_keys=tag_keys).set_default_tags(tags)

        def hist(name, desc, bounds):
            return _metrics.internal_metric(
                _metrics.Histogram, name, desc, boundaries=bounds,
                tag_keys=("node",)).set_default_tags(tags)

        self._im = {
            "queue_depth": gauge(
                "ray_tpu_internal_scheduler_queue_depth",
                "Tasks in the raylet ready queue"),
            "waiting": gauge(
                "ray_tpu_internal_scheduler_waiting_tasks",
                "Tasks blocked on unresolved arguments"),
            "worker_pool": gauge(
                "ray_tpu_internal_worker_pool_size",
                "Pooled (non-actor) worker processes"),
            "objects": gauge(
                "ray_tpu_internal_objects_tracked",
                "Objects tracked by this raylet"),
            "store_bytes": gauge(
                "ray_tpu_internal_object_store_bytes_used",
                "Bytes sealed in the shm object store"),
            "spilled_bytes": gauge(
                "ray_tpu_internal_object_store_spilled_bytes",
                "Bytes spilled from the store to disk"),
            "tasks_total": counter(
                "ray_tpu_internal_tasks_total",
                "Terminal task states seen by this raylet",
                tag_keys=("node", "state")),
            "events_dropped": counter(
                "ray_tpu_internal_task_events_dropped_total",
                "Task events shed by the export ring buffer"),
            "trace_dropped": counter(
                "ray_tpu_internal_trace_spans_dropped_total",
                "Trace spans shed by the export buffers (process-local "
                "and raylet-side) before reaching the GCS trace table"),
            "profile_dropped": counter(
                "ray_tpu_internal_profile_samples_dropped_total",
                "Folded profile sample records shed by the export "
                "buffers before reaching the GCS profile table"),
            "metric_points_dropped": counter(
                "ray_tpu_internal_metric_points_dropped_total",
                "Metric time-series delta points shed by the export "
                "rings before reaching the GCS metrics table"),
            "telemetry_flush_s": counter(
                "ray_tpu_internal_telemetry_flush_seconds_total",
                "Telemetry self-audit: wall seconds spent in export "
                "flush paths, by subsystem",
                tag_keys=("node", "subsystem")),
            "telemetry_flush_bytes": counter(
                "ray_tpu_internal_telemetry_flush_bytes_total",
                "Telemetry self-audit: approximate bytes shipped by "
                "export flush paths, by subsystem",
                tag_keys=("node", "subsystem")),
            "frames": counter(
                "ray_tpu_internal_proto_frames_total",
                "Control-plane frames handled"),
            "trains": counter(
                "ray_tpu_internal_proto_trains_total",
                "Socket drains (coalesced frame trains)"),
            "dispatch_latency": hist(
                "ray_tpu_internal_dispatch_latency_s",
                "Queue-ready to dispatch latency",
                (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)),
            "train_bytes": hist(
                "ray_tpu_internal_proto_train_bytes",
                "Bytes received per socket drain",
                (256, 4096, 65536, 1 << 20)),
            "gcs_rpc_latency": hist(
                "ray_tpu_internal_gcs_rpc_latency_s",
                "Blocking GCS client RPC round-trip latency",
                (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 1.0)),
            # ---- data plane (pull manager / data channel) ----
            "pull_inflight_bytes": gauge(
                "ray_tpu_internal_pull_inflight_bytes",
                "Bytes of admitted in-flight data-plane pulls"),
            "pull_queued": gauge(
                "ray_tpu_internal_pull_queued",
                "Pulls waiting in the admission queue"),
            "pull_active": gauge(
                "ray_tpu_internal_pull_active",
                "Admitted data-plane pulls in progress"),
            "pull_bytes": counter(
                "ray_tpu_internal_pull_bytes_total",
                "Object bytes received over the data plane"),
            "pull_chunks": counter(
                "ray_tpu_internal_pull_chunks_total",
                "Chunk ranges received over the data plane"),
            "pull_source_switches": counter(
                "ray_tpu_internal_pull_source_switches_total",
                "Pull ranges rotated to another holder (stall/failure)"),
            "pull_multi_source": counter(
                "ray_tpu_internal_pull_multi_source_total",
                "Completed pulls that striped across >= 2 holders"),
            "pull_sender_saturated": counter(
                "ray_tpu_internal_pull_sender_saturated_total",
                "Fallback pull-serve submissions that queued behind a "
                "fully busy sender pool"),
            "locality_spills": counter(
                "ray_tpu_internal_locality_spills_total",
                "Tasks forwarded to the node holding their argument bytes"),
            # ---- lineage reconstruction (node death / eviction recovery) --
            "recon_attempts": counter(
                "ray_tpu_internal_reconstruction_attempts_total",
                "Creating-task re-runs started to recover lost objects"),
            "recon_successes": counter(
                "ray_tpu_internal_reconstruction_successes_total",
                "Reconstruction attempts whose returns re-sealed"),
            "recon_failures": counter(
                "ray_tpu_internal_reconstruction_failures_total",
                "Reconstruction attempts whose returns errored"),
            "recon_depth": hist(
                "ray_tpu_internal_reconstruction_depth",
                "Recursion depth at which reconstructions were started "
                "(dependency chains re-run below the lost object)",
                (1, 2, 4, 8)),
            # ---- eager availability (replication + actor checkpoints) ----
            "repl_pushes": counter(
                "ray_tpu_internal_replication_pushes_total",
                "Secondary-copy pushes initiated for sealed objects"),
            "repl_bytes": counter(
                "ray_tpu_internal_replication_bytes_total",
                "Object bytes covered by replication pushes"),
            "repl_repairs": counter(
                "ray_tpu_internal_replication_repairs_total",
                "Re-replications after a holder died (copy count "
                "restored)"),
            "repl_recoveries": counter(
                "ray_tpu_internal_replication_recoveries_total",
                "Node-death object losses recovered from a surviving "
                "copy instead of lineage recompute"),
            "ckpt_saves": counter(
                "ray_tpu_internal_checkpoint_saves_total",
                "Actor state checkpoints recorded"),
            "ckpt_bytes": counter(
                "ray_tpu_internal_checkpoint_bytes_total",
                "Serialized actor checkpoint bytes recorded"),
            "ckpt_restores": counter(
                "ray_tpu_internal_checkpoint_restores_total",
                "Actor restarts that restored from a checkpoint instead "
                "of starting cold"),
            # ---- overload protection & deadlines ----
            "shed": counter(
                "ray_tpu_internal_shed_total",
                "Requests rejected by overload protection (bounded-queue "
                "admission, lowest-deadline-headroom victim policy)"),
            "deadline_exceeded": counter(
                "ray_tpu_internal_deadline_exceeded_total",
                "Tasks whose end-to-end deadline expired (admission, "
                "queue, or pre-dispatch enforcement on this node)"),
            "cancelled": counter(
                "ray_tpu_internal_cancelled_total",
                "Tasks cancelled (explicit cancel + recursive fan-out)"),
            # ---- failure detection / fencing ----
            "fenced_frames": counter(
                "ray_tpu_internal_fenced_frames_total",
                "Stale node-attributed frames rejected by incarnation "
                "fencing (peer hellos / data-channel handshakes from a "
                "declared-dead incarnation)"),
        }
        self._im_producer = f"raylet-{os.getpid()}-{self.node_id[:8]}"
        # time-series baselines for collect_points (metrics tick only)
        self._im_points_last: Dict = {}
        if isinstance(self.gcs, GcsClient):
            self.gcs.rpc_observer = self._observe_gcs_rpc

    def _observe_gcs_rpc(self, op: str, seconds: float):
        # Called from whichever thread issued the RPC; observe() locks.
        if self._im is not None:
            self._im["gcs_rpc_latency"].observe(seconds)

    def _spilled_bytes(self) -> int:
        store = self._store  # unguarded-ok: atomic reference read (metrics sampling)
        spill_dir = getattr(store, "_spill_dir", None)
        if not spill_dir or not os.path.isdir(spill_dir):
            return 0
        total = 0
        try:
            with os.scandir(spill_dir) as it:
                for entry in it:
                    try:
                        total += entry.stat().st_size
                    except OSError:
                        pass
        except OSError:
            return 0
        return total

    def _flush_internal_metrics(self):
        """Sample event-thread state into the internal metric set and push
        the payloads under this raylet's own producer key (merged with user
        metrics by the dashboard's /metrics renderer)."""
        # Re-arm FIRST (the callback runs under _safe): an exception mid-
        # flush — e.g. a transient store-attach failure — must not silently
        # kill the export for the life of the raylet.
        if not self._shutdown:
            self.add_timer(config.internal_metrics_interval_s,
                           self._flush_internal_metrics)
        im = self._im
        im["queue_depth"].set(len(self._ready_queue))
        im["waiting"].set(len(self._waiting))
        im["worker_pool"].set(sum(
            1 for c in self._workers.values()
            if c.actor_id is None and c.state in ("idle", "busy")))
        im["objects"].set(len(self._objects))
        store = self._raylet_store()
        if store is not None and hasattr(store, "stats"):
            try:
                im["store_bytes"].set(store.stats()["bytes_in_use"])
            except Exception:  # noqa: BLE001
                pass
            im["spilled_bytes"].set(self._spilled_bytes())

        def bump(counter, key, value, tags=None):
            delta = value - self._m_last.get(key, 0)
            if delta > 0:
                counter.inc(delta, tags=tags)
            self._m_last[key] = value

        bump(im["frames"], "frames", self._m_frames)
        bump(im["trains"], "trains", self._m_trains)
        bump(im["events_dropped"], "dropped", self._task_event_dropped_total)
        bump(im["trace_dropped"], "trace_dropped", self._trace_dropped_total)
        bump(im["profile_dropped"], "profile_dropped",
             self._profile_dropped_total)
        for st, n in self._m_tasks_done.items():
            bump(im["tasks_total"], f"tasks_{st}", n, tags={"state": st})
        bump(im["pull_sender_saturated"], "pull_sat",
             self._m_pull_sender_saturated)
        bump(im["locality_spills"], "loc_spills", self._m_locality_spills)
        bump(im["recon_attempts"], "recon_att", self._m_recon_attempts)
        bump(im["recon_successes"], "recon_ok", self._m_recon_successes)
        bump(im["recon_failures"], "recon_fail", self._m_recon_failures)
        bump(im["repl_pushes"], "repl_push", self._m_repl_pushes)
        bump(im["repl_bytes"], "repl_bytes", self._m_repl_bytes)
        bump(im["repl_repairs"], "repl_repair", self._m_repl_repairs)
        bump(im["repl_recoveries"], "repl_recover", self._m_repl_recoveries)
        bump(im["ckpt_saves"], "ckpt_saves", self._m_ckpt_saves)
        bump(im["ckpt_bytes"], "ckpt_bytes", self._m_ckpt_bytes)
        bump(im["ckpt_restores"], "ckpt_restores", self._m_ckpt_restores)
        bump(im["fenced_frames"], "fenced_frames", self._m_fenced_frames)
        bump(im["shed"], "shed", self._m_shed)
        bump(im["deadline_exceeded"], "deadline_exceeded",
             self._m_deadline_exceeded)
        bump(im["cancelled"], "cancelled", self._m_cancelled)
        bump(im["metric_points_dropped"], "mpoints_dropped",
             self._metric_points_dropped_total)
        for sub, slot in self._m_telemetry.items():
            bump(im["telemetry_flush_s"], f"tel_s_{sub}", slot[0],
                 tags={"subsystem": sub})
            bump(im["telemetry_flush_bytes"], f"tel_b_{sub}", slot[1],
                 tags={"subsystem": sub})
        if self._pull_manager is not None:
            ps = self._pull_manager.stats()
            im["pull_inflight_bytes"].set(ps["inflight_bytes"])
            im["pull_queued"].set(ps["queued"])
            im["pull_active"].set(ps["active"])
            bump(im["pull_bytes"], "pull_bytes", ps["bytes_total"])
            bump(im["pull_chunks"], "pull_chunks", ps["chunks_total"])
            bump(im["pull_source_switches"], "pull_switch",
                 ps["source_switches"])
            bump(im["pull_multi_source"], "pull_multi",
                 ps["multi_source_pulls"])

        import json as _json

        t0 = time.perf_counter()
        items = []
        for m in im.values():
            payload = m._export()
            if payload is None:
                continue
            items.append((f"{self._im_producer}/{m.name}".encode(),
                          _json.dumps(payload).encode()))
        if items:
            # one post for the whole metric set (~30 keys), not one per key
            self._gcs_post("kv_multi_put", "metrics", items)
        self._audit_flush("metrics", t0,
                          nbytes=sum(len(k) + len(v) for k, v in items))
        if config.metrics_history:
            # the same cadence ships DELTA points into the GCS metrics
            # time-series table: this raylet's internal set, the local
            # registry ring (driver-process user/serve metrics), and
            # whatever workers shipped since the last tick
            points = _metrics_mod.collect_points(im.values(),
                                                 self._im_points_last)
            if points:
                self._metric_points_ingest(points)
            self.flush_metric_points()

    def state_snapshot(self, objects_limit: int = 0) -> dict:
        return {
            "node_id": self.node_id,
            "resources_total": dict(self.resources_total),
            "resources_available": dict(self.resources_available),
            "num_workers": len(self._workers),
            "tasks": list(self._task_states.values()),
            "actors": [
                {
                    "actor_id": a.actor_id.hex(),
                    "state": a.state,
                    "name": a.name,
                    "pid": a.conn.pid if a.conn else None,
                }
                for a in self._actors.values()
            ],
            "objects": {
                "num": len(self._objects),
                # detail rows only on request (``objects_limit`` > 0): the
                # limit applies HERE, at the source, before materializing —
                # and reading on the event thread makes the iteration safe.
                "items": [
                    {
                        "object_id": oid.hex(),
                        "status": st.status,
                        "size": st.size,
                        "locations": list(st.locations),
                    }
                    for oid, st in itertools.islice(
                        self._objects.items(), max(0, objects_limit))
                ] if objects_limit > 0 else None,
            },
            "placement_groups": [
                {"id": pg.pg_id, "state": pg.state,
                 "bundles": list(pg.bundles.values()),
                 "fragment": pg.fragment}
                for pg in self._pgs.values()
            ],
            "events": list(self._task_events),
        }

    # --------------------------------------------------------------- shutdown

    def shutdown(self):
        try:
            self.gcs.unregister_node(self.node_id)
        except Exception:  # noqa: BLE001
            pass
        self._shutdown = True
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass
        self._thread.join(timeout=5)
        if isinstance(self.gcs, GcsClient):
            self.gcs.close()
        for p in self._procs:
            try:
                p.terminate()
                p.wait(timeout=2)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    p.kill()
                except OSError:
                    pass
