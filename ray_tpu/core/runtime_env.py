"""Runtime environments: per-task/actor working_dir, py_modules, env_vars.

Reference analogue: `python/ray/_private/runtime_env/` — ``working_dir``
and ``py_modules`` are zipped, content-addressed, shipped through the GCS
KV store, and extracted into a per-URI cache on the executing node
(`packaging.py`: zip->GCS; `working_dir.py`: download+extract).  ``pip``/
``conda`` envs are declared but rejected here: the TPU image is hermetic
(no network), matching the deployment model where dependencies bake into
the image.

Flow:
  driver: prepare_runtime_env(env) zips local dirs -> kv["rtenv:<sha>"],
          rewrites the env to {"working_dir_uri": sha, ...};
  worker: ensure_runtime_env(env) fetches+extracts each URI once per node
          (cache keyed by sha), chdirs / extends sys.path.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Optional

_MAX_PACKAGE_BYTES = 256 << 20
_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules"}


def _zip_dir(path: str) -> bytes:
    """Deterministic zip: sorted entries, zeroed timestamps — identical
    content hashes identically across machines/checkouts (mtimes would
    defeat the content-addressed KV dedup)."""
    buf = io.BytesIO()
    base = os.path.abspath(path)
    entries = []
    for root, dirs, files in os.walk(base):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for f in sorted(files):
            full = os.path.join(root, f)
            entries.append((os.path.relpath(full, base), full))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in sorted(entries):
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            info.compress_type = zipfile.ZIP_DEFLATED
            info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            with open(full, "rb") as fh:
                zf.writestr(info, fh.read())
            if buf.tell() > _MAX_PACKAGE_BYTES:
                raise ValueError(
                    f"runtime_env package {path} exceeds "
                    f"{_MAX_PACKAGE_BYTES >> 20}MB")
    return buf.getvalue()


def _dir_signature(path: str) -> tuple:
    """Cheap change signature (no content reads) for the driver-side
    packaging cache: (count, total size, max mtime_ns)."""
    count = size = 0
    newest = 0
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
        for f in files:
            try:
                st = os.stat(os.path.join(root, f))
            except OSError:
                continue
            count += 1
            size += st.st_size
            newest = max(newest, st.st_mtime_ns)
    return (count, size, newest)


_package_cache: dict = {}  # (abspath, signature) -> sha


def _kv_key(sha: str) -> bytes:
    return f"rtenv:{sha}".encode()


def prepare_runtime_env(worker, env: Optional[dict]) -> Optional[dict]:
    """Driver-side: package local dirs into the GCS KV, returning an env
    whose dirs are content-addressed URIs (idempotent per content)."""
    if not env:
        return env
    if env.get("pip") or env.get("conda"):
        raise ValueError(
            "runtime_env pip/conda are not supported on the hermetic TPU "
            "image — bake dependencies into the image (reference parity: "
            "python/ray/_private/runtime_env/pip.py)")
    out = dict(env)
    wd = env.get("working_dir")
    if wd:
        if not os.path.isdir(wd):
            raise ValueError(
                f"runtime_env working_dir {wd!r} does not exist")
        out.pop("working_dir")
        out["working_dir_uri"] = _package_dir(worker, wd)
    mods = env.get("py_modules")
    if mods:
        uris = []
        for m in mods:
            if not os.path.isdir(m):
                raise ValueError(f"py_modules entry {m!r} is not a dir")
            uris.append((_package_dir(worker, m),
                         os.path.basename(os.path.abspath(m))))
        out.pop("py_modules")
        out["py_modules_uris"] = uris
    return out


def _package_dir(worker, path: str) -> str:
    """zip+hash+upload once per (path, content signature) — repeated
    .remote() calls with the same env skip the packaging work entirely."""
    key = (os.path.abspath(path), _dir_signature(path))
    sha = _package_cache.get(key)
    if sha is not None:
        return sha
    blob = _zip_dir(path)
    sha = hashlib.sha1(blob).hexdigest()
    if worker.kv_get(_kv_key(sha)) is None:
        worker.kv_put(_kv_key(sha), blob)
    _package_cache[key] = sha
    return sha


def _cache_root() -> str:
    from ray_tpu.core.config import config

    return os.path.join(config.temp_dir, "runtime_envs")


def _ensure_extracted(worker, sha: str) -> str:
    dest = os.path.join(_cache_root(), sha)
    if os.path.isdir(dest):
        return dest
    blob = worker.kv_get(_kv_key(sha))
    if blob is None:
        raise RuntimeError(f"runtime_env package {sha} missing from GCS KV")
    tmp = dest + f".tmp{os.getpid()}"
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.replace(tmp, dest)
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)  # raced another worker
    return dest


def ensure_runtime_env(worker, env: Optional[dict]):
    """Worker-side: materialize URIs, chdir into the working dir, extend
    sys.path for py_modules (reference: per-URI cache in
    `runtime_env/working_dir.py`)."""
    if not env:
        return
    sha = env.get("working_dir_uri")
    if sha:
        dest = _ensure_extracted(worker, sha)
        os.chdir(dest)
        if dest not in sys.path:
            sys.path.insert(0, dest)
    wd = env.get("working_dir")
    if wd:  # same-host local path (un-packaged, e.g. internal callers)
        os.chdir(wd)  # raises if missing — don't run in a stale cwd
        if wd not in sys.path:
            sys.path.insert(0, wd)
    for sha, name in env.get("py_modules_uris", ()):
        dest = _ensure_extracted(worker, sha)
        # importable as <name>: expose a parent dir containing the module
        parent = os.path.join(_cache_root(), f"mod_{sha}")
        os.makedirs(parent, exist_ok=True)
        link = os.path.join(parent, name)
        if not os.path.exists(link):
            try:
                os.symlink(dest, link)
            except OSError:
                pass  # raced another worker
        if parent not in sys.path:
            sys.path.insert(0, parent)
