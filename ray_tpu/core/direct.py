"""Direct worker→worker call transport — peer-to-peer actor calls and
lease-reused tasks, with the raylet demoted to broker.

Reference analogue: the core worker's direct actor/task submitters
(`src/ray/core_worker/transport/direct_actor_transport.h`,
`direct_task_transport.h`): after the first raylet-brokered call resolves
an actor (or leases a pool worker), the caller's process dials the callee
worker's process directly and the callee pushes results straight back —
the raylet's submit→inbox→dispatch→done round trip leaves the critical
path entirely.

Roles (both live in this module so the wire format has one home):

* ``DirectServer`` — callee side, hosted by every worker subprocess: a
  listening socket (unix always; TCP too in cluster mode) whose address
  rides the worker's ``register`` message.  Accepted callers are
  validated against the PR 8 fencing state (node incarnation) and the
  actor's restart generation before any call is accepted.  Executed
  results are remembered in a bounded dedup cache so a retried call
  (new channel, or a raylet-path reconcile) re-sends the recorded result
  instead of re-executing.
* ``DirectCallClient`` — caller side, hosted by drivers and workers:
  per-actor (and per-lease) connection cache, pending-call table the
  caller's ``get()`` resolves against, and the fallback machinery — on
  channel death, fence notice, or a stale-after-freeze reject, in-flight
  calls are resubmitted through the raylet with ``_direct_retry`` set,
  where the resolved-skip + actor-generation checks give the existing
  retryable-``ActorDiedError`` semantics with zero double-execution.

Ordering: a caller switches an actor to the direct path only once it has
observed every previously relayed call to that actor complete (via get /
wait), and from then on all its eligible calls ride one FIFO socket — so
per-handle call order is preserved across the switch.  Calls that are
ineligible (ObjectRef args, streaming returns, ``__ray_terminate__``)
stay on the raylet path.

Bookkeeping: the callee notifies its raylet of every direct completion
with a ``direct_done`` frame (off the caller's critical path), so object
state, ref counting, task events, lineage (lease tasks), and replication
behave exactly as on the relayed path; the raylet just stops being a hop
in the caller's round trip.

Freeze gate: a process resumed from a long stop (SIGSTOP partition — the
PR 8 chaos scenario) must not execute direct frames that sat in its
kernel buffer across the freeze: by then the cluster may have fenced the
node and restarted the actor elsewhere.  A 100ms ticker detects the gap;
whichever thread first observes ``now - last_tick`` beyond the gate marks
every live conn stale, and stale conns reject (never execute) their
calls — the caller reconciles through the raylet, which fences on the
actor generation.
"""

from __future__ import annotations

import os
import select
import socket
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.core import protocol
from ray_tpu.core.config import config
from ray_tpu.core.task_spec import (
    ACTOR_TASK,
    NORMAL_TASK,
    STREAMING_RETURNS,
    TaskSpec,
)
from ray_tpu.util.locks import make_lock

config.define("direct_calls", bool, True,
              "Direct worker→worker call transport: after the first "
              "raylet-brokered call, actor calls (and idle-channel "
              "lease-reused tasks) travel caller→callee directly and "
              "results push straight back; the raylet only brokers "
              "address + lease + fencing incarnation and keeps the "
              "relayed path as first-call/recovery fallback.  "
              "RAY_TPU_DIRECT_CALLS=0 is the kill switch (bench A/B, "
              "debugging).")
config.define("direct_dedup_cache", int, 1024,
              "Callee-side executed-result cache entries (per worker): a "
              "retried direct call whose original execution completed "
              "re-sends the recorded result instead of re-executing — "
              "the exactly-once half of partition recovery.")
config.define("direct_result_cache", int, 8192,
              "Caller-side resolved direct-result cache entries; evicted "
              "results fall back to the raylet get path (the callee's "
              "direct_done already registered them there).")
config.define("direct_connect_timeout_s", float, 5.0,
              "Dial + hello timeout for establishing a direct channel; "
              "on expiry the call falls back to the raylet path and the "
              "actor is retried after a short backoff.")
config.define("direct_lease_idle_s", float, 1.0,
              "A leased pool worker (direct normal-task channel) is "
              "returned to its raylet after this long with no call in "
              "flight, bounding how long an idle lease can hold pool "
              "capacity.")
config.define("direct_pipeline_depth", int, 64,
              "Max direct calls in flight per channel before submit() "
              "drains results (blocking): bounds both sides' socket "
              "buffers so a fire-and-forget burst ping-pongs smoothly "
              "instead of wedging in sendall, and bounds how many calls "
              "can need reconciling after a teardown.")
config.define("direct_burst", bool, True,
              "Coalesced direct burst mode: async actor calls and "
              "fast-turnover lease-reused tasks pipeline over the direct "
              "channel with a windowed ack (each dresult acks one slot; "
              "submit() demuxes the socket past direct_burst_window in "
              "flight) instead of draining the window and falling back "
              "to the relayed path; outbound dcalls and callee-side "
              "raylet notes coalesce into one batched frame per flush "
              "window.  RAY_TPU_DIRECT_BURST=0 is the kill switch and "
              "restores the pre-burst drain-and-relay behavior exactly.")
config.define("direct_burst_window", int, 64,
              "Burst-mode window W: max unacked direct calls in flight "
              "per channel.  Past W the submitting thread advances the "
              "window by demuxing results (no per-call round trip, no "
              "relayed hand-back), bounding both sides' socket buffers "
              "and the reconcile set after a teardown.  Default chosen "
              "from the bench_core burst-depth sweep (throughput rises "
              "with W up to the socket-buffer knee; 64 ≈ the plateau).")
config.define("direct_lease_turnover_ms", float, 2.0,
              "Lease channels pipeline a burst (instead of spreading the "
              "fan-out over the pool) only once the channel's observed "
              "per-call turnover EWMA sits below this many milliseconds: "
              "sub-ms tasks gain more from pipelined submission than "
              "from pool parallelism, while longer tasks keep the "
              "serial-reuse + relayed-spread behavior.  The callee "
              "stamps the turnover (decode→result) into each burst-mode "
              "dresult.")
config.define("direct_freeze_gate_s", float, 3.0,
              "Callee freeze detector: if the worker process observes a "
              "scheduling gap longer than this (SIGSTOP partition, VM "
              "pause), direct frames buffered across the gap are "
              "rejected instead of executed — the caller reconciles via "
              "the raylet, which fences on the actor generation.  "
              "Conservative by default: a false trip (ticker starved on "
              "an overloaded host) is safe but costs a teardown + "
              "relayed round trip, so the gate sits well above ordinary "
              "scheduler jitter while far below partition-detection + "
              "failover time.")

_DIAL_ERRORS = (OSError, protocol.ProtocolError, TimeoutError)


def _trace_ctx(spec: TaskSpec):
    """Sampled trace context of a spec, or None — the unsampled fast
    path: 99% of calls at the default 1% sampling pay two dict probes
    here and zero span traffic (the PR 9 discipline, applied to the new
    hops)."""
    ctx = spec.trace_ctx
    if ctx is None or not ctx.get("sampled", True):
        return None
    from ray_tpu.util import tracing

    if not tracing.tracing_enabled():
        return None
    return ctx


# ---------------------------------------------------------------------------
# Callee side


class _DirectConn:
    """One accepted caller connection on the callee worker."""

    __slots__ = ("sock", "send_lock", "alive", "stale", "hello",
                 "coalesce", "_out", "note_buf")

    def __init__(self, sock):
        self.sock = sock
        self.send_lock = make_lock("direct.conn.send")
        self.alive = True
        self.stale = False  # frames may predate a detected freeze
        self.hello: Optional[dict] = None
        # Result coalescing: while the conn thread still has decoded
        # calls backlogged (a pipelined burst), results buffer here and
        # flush in ONE sendall when the backlog drains — bursts pay one
        # syscall per train, sync calls still reply immediately.
        # coalesce is flipped only by the conn thread itself.
        self.coalesce = False
        self._out: List[dict] = []  # conn-thread only
        # Raylet-note coalescing (burst mode): direct_running/direct_done
        # notes from this train's inline executions buffer here and ship
        # as ONE direct_notes frame at train drain — one ref-event flush
        # and one done-buffer lock per train instead of two per call.
        self.note_buf: List[dict] = []  # conn-thread only

    def flush_notes(self, worker):
        if not self.note_buf:
            return
        notes, self.note_buf = self.note_buf, []
        worker.queue_direct_notes(notes)

    def send_result(self, msg):
        if self.coalesce:
            self._out.append(msg)
            return
        try:
            protocol.send_msg(self.sock, msg, self.send_lock)
        except OSError:
            self.alive = False

    def flush_results(self):
        if not self._out:
            return
        out, self._out = self._out, []
        try:
            protocol.send_msgs(self.sock, out, self.send_lock)
        except OSError:
            self.alive = False


class DirectServer:
    """Callee-side listener hosted by a worker subprocess.

    Accepts direct channels, validates hellos against incarnation +
    actor generation, enqueues calls into the worker's ordinary task
    queue (FIFO with raylet dispatches), and remembers executed results
    for retry dedup.
    """

    def __init__(self, worker, sock_dir: str):
        self._worker = worker
        self._listeners: List[socket.socket] = []
        self.unix_path = os.path.join(sock_dir, f"direct-{os.getpid()}.sock")
        if os.path.exists(self.unix_path):
            os.unlink(self.unix_path)
        lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        lsock.bind(self.unix_path)
        lsock.listen(64)
        self._listeners.append(lsock)
        tcp_addr = None
        node_ip = config.node_ip
        if node_ip:
            # cluster mode: remote callers (drivers/workers on peers) dial
            # over TCP; the raylet stamps RAY_TPU_NODE_IP into our env
            try:
                tsock = socket.create_server((node_ip, 0), backlog=64)
                self._listeners.append(tsock)
                tcp_addr = (node_ip, tsock.getsockname()[1])
            except OSError:
                tcp_addr = None
        self.addr = {"unix": self.unix_path, "tcp": tcp_addr,
                     "hostname": socket.gethostname()}
        self.node_incarnation = config.node_incarnation
        # Executed-result dedup: task_id -> done record (guard: _dedup_lock)
        self._dedup: "OrderedDict[Any, dict]" = OrderedDict()
        self._dedup_lock = make_lock("direct.server.dedup")
        # Direct calls admitted but not yet completed, and raylet-path
        # reconciles parked on one of them (guard: _dedup_lock).  A
        # reconcile arriving while the ORIGINAL direct execution is still
        # running must neither re-execute (double side effects) nor drop
        # (the raylet awaits a done): it defers, and remember() answers
        # it with the recorded result at completion.
        self._inflight: set = set()
        self._deferred: set = set()
        self._conns: List[_DirectConn] = []  # guard: _conns_lock
        self._conns_lock = make_lock("direct.server.conns")
        # Freeze detector: last_tick is advanced by the ticker thread; any
        # thread observing a gap beyond the gate marks live conns stale
        # BEFORE the tick resets (see _tick_loop), so buffered frames from
        # before a SIGSTOP can never race past the check.
        self.last_tick = time.monotonic()
        for lsock in self._listeners:
            threading.Thread(target=self._accept_loop, args=(lsock,),
                             name="direct-accept", daemon=True).start()
        threading.Thread(target=self._tick_loop, name="direct-ticker",
                         daemon=True).start()

    # ---- freeze detection ----

    def _tick_loop(self):
        while True:
            time.sleep(0.1)
            gap = time.monotonic() - self.last_tick
            if gap > config.direct_freeze_gate_s:
                self._mark_stale()
            self.last_tick = time.monotonic()

    def _mark_stale(self):
        with self._conns_lock:
            for conn in self._conns:
                conn.stale = True

    def _conn_is_stale(self, conn: _DirectConn) -> bool:
        if time.monotonic() - self.last_tick > config.direct_freeze_gate_s:
            # this thread saw the gap first: fence every conn (including
            # this one) before the ticker resets the clock
            self._mark_stale()
        return conn.stale

    # ---- dedup cache ----

    def remember(self, task_id, done: dict):
        # Stored by reference, not copied: _deliver_result hands the done
        # dict here and never mutates it afterwards (its wire sends copy
        # first), and every reader (lookup / admit / reconcile_probe /
        # the deferred answer below) copies before stamping t/task_id.
        rec = done
        with self._dedup_lock:
            self._dedup[task_id] = rec
            self._inflight.discard(task_id)
            deferred = task_id in self._deferred
            self._deferred.discard(task_id)
            while len(self._dedup) > config.direct_dedup_cache:
                self._dedup.popitem(last=False)
        if deferred:
            # a raylet-path reconcile parked on this execution: answer its
            # dispatch with the recorded result (never a second run)
            ans = dict(rec)
            ans["t"] = "done"
            ans["task_id"] = task_id
            self._worker.send_done(ans)

    def lookup(self, task_id) -> Optional[dict]:
        with self._dedup_lock:
            rec = self._dedup.get(task_id)
            return dict(rec) if rec is not None else None

    def admit(self, task_id):
        """Atomic dedup-or-mark-inflight for an arriving dcall: returns
        (cached, busy) — a cached result to re-send, or busy=True when
        the same task is already queued/executing here (the caller must
        reconcile via the raylet, not run it twice).  busy shouldn't
        happen with the reconcile-only retry flow, but a second direct
        submission of an in-flight task must never execute."""
        with self._dedup_lock:
            rec = self._dedup.get(task_id)
            if rec is not None:
                return dict(rec), False
            if task_id in self._inflight:
                return None, True
            self._inflight.add(task_id)
            return None, False

    def reconcile_probe(self, task_id):
        """For a raylet-dispatched spec: (cached, deferred).  cached =>
        already executed directly, re-send the recorded done; deferred =>
        the direct execution is in flight and remember() will answer this
        dispatch at completion — the caller skips execution either way."""
        with self._dedup_lock:
            rec = self._dedup.get(task_id)
            if rec is not None:
                return dict(rec), False
            if task_id in self._inflight:
                self._deferred.add(task_id)
                return None, True
            return None, False

    # ---- accept / per-conn reader ----

    def _accept_loop(self, listener):
        while True:
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass  # unix socket
            try:
                # Send timeout only (recv stays blocking): the caller
                # demuxes results from get()/submit(), so a caller that
                # stops consuming could otherwise wedge this worker in
                # sendall once the kernel buffer fills.  On expiry the
                # conn drops; the raylet path (direct_done already sent)
                # still serves the results.
                import struct as _struct

                sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                                _struct.pack("ll", 10, 0))
            except OSError:
                pass
            conn = _DirectConn(sock)
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             name="direct-serve", daemon=True).start()

    def _check_hello(self, msg: dict) -> Optional[str]:
        """None = accepted; else the rejection reason (the fencing seam:
        a stale caller — old incarnation, or a generation from before the
        actor's last restart — must never get calls executed here)."""
        worker = self._worker
        if msg.get("incarnation", 0) < self.node_incarnation:
            return "stale node incarnation (fenced)"
        aid = msg.get("actor_id")
        if aid is not None:
            if worker.actor_instance is None \
                    or worker.current_actor_id != aid:
                return "worker hosts no such actor"
            if msg.get("generation", 0) != worker.actor_generation:
                return "stale actor generation (restarted)"
        elif worker.actor_instance is not None:
            return "worker is an actor, not leasable"
        else:
            # Lease channel: the raylet told us which lease it granted
            # (direct_lease control message) — a dialer without that
            # exact token must not execute tasks here, or it would
            # bypass the raylet's resource accounting entirely.  The
            # grant rides the raylet→worker socket while the caller
            # dials on the lease reply, so tolerate a short in-flight
            # window before rejecting.
            lid = msg.get("lease_id")
            if lid is None:
                return "no lease presented"
            deadline = time.monotonic() + 1.0
            while getattr(worker, "active_lease_id", None) != lid:
                if time.monotonic() > deadline:
                    return "lease not granted by the raylet"
                time.sleep(0.005)
        return None

    def _handle_call(self, conn: _DirectConn, msg: dict, trailing: bool):
        """One dcall (possibly unpacked from a dburst frame): dedup-admit
        and execute inline / enqueue.  ``trailing`` = more calls are
        already decoded behind this one, so results (and burst-mode
        raylet notes) coalesce into the train's batched flush."""
        spec: TaskSpec = msg["spec"]
        if self._conn_is_stale(conn) or conn.hello is None:
            # frames possibly buffered across a freeze (or a
            # caller skipping the handshake): refuse — the
            # caller reconciles via the raylet path
            conn.send_result({"t": "dresult",
                              "task_id": spec.task_id,
                              "ok": False, "rejected": True})
            return
        cached, busy = self.admit(spec.task_id)
        if cached is not None:
            # retried call whose first execution completed:
            # re-send the recorded result, never re-execute
            cached["t"] = "dresult"
            cached["task_id"] = spec.task_id
            conn.send_result(cached)
            return
        if busy:
            # already queued/executing here (duplicate direct
            # submission): refuse — the caller reconciles via
            # the raylet, which defers on the same execution
            conn.send_result({"t": "dresult",
                              "task_id": spec.task_id,
                              "ok": False, "rejected": True})
            return
        task_msg = {"t": "task", "spec": spec,
                    "arg_values": msg.get("arg_values") or {},
                    "direct_conn": conn}
        worker = self._worker
        if (worker.actor_loop is None
                and worker.group_executors is None
                and worker.actor_executor is None):
            # Plain sync actor / leased pool worker: execute
            # RIGHT HERE on the conn thread — the queue
            # handoff to the main executor thread is a full
            # scheduler wakeup of dead time per call.  The
            # exec lock serializes against the main loop, so
            # single-threaded execution semantics hold.
            from ray_tpu.core import worker_main

            # results coalesce while more calls are decoded
            # and waiting (one sendall per burst train; the
            # loop top flushes when the train drains)
            task_msg["_inline"] = True
            task_msg["_rx_t"] = time.time()
            conn.coalesce = trailing
            with worker.exec_lock:
                worker_main.execute_task(worker, task_msg)
        else:
            # asyncio / concurrency-group actors: route
            # through the main loop's dispatch logic
            worker.task_queue.put(task_msg)

    def _conn_loop(self, conn: _DirectConn):
        reader = protocol.FrameReader(conn.sock)
        try:
            while True:
                if not reader._pending:
                    # end of a decoded train: ship any coalesced results
                    # (and buffered raylet notes) before blocking for the
                    # next frame
                    conn.coalesce = False
                    conn.flush_results()
                    conn.flush_notes(self._worker)
                try:
                    msg = reader.recv_msg()
                except (OSError, protocol.ProtocolError):
                    msg = None
                if msg is None:
                    break
                t = msg.get("t")
                if t == "dhello":
                    reason = self._check_hello(msg)
                    conn.hello = msg
                    conn.send_result({"t": "dhello_ack",
                                      "ok": reason is None,
                                      "reason": reason,
                                      "pid": os.getpid()})
                    if reason is not None:
                        break
                elif t == "dcancel":
                    # cancel frame for a call submitted on THIS channel:
                    # mark it in the in-flight registry (pre-exec check)
                    # and interrupt it if it is executing right now on a
                    # pool/loop thread (actor channels).  Lease channels
                    # execute dcalls INLINE on this very conn thread, so
                    # a same-channel dcancel is only read after the call
                    # finishes — mid-exec interrupts for those arrive via
                    # the raylet's control-socket cancel frame instead
                    # (the reader thread delivers the async exception).
                    self._worker.cancel_registry.cancel(msg["task_id"])
                elif t == "dcall":
                    self._handle_call(conn, msg, bool(reader._pending))
                elif t == "dburst":
                    # one coalesced flush window from the caller: unpack
                    # in order; every call but the last has decoded work
                    # behind it by construction
                    calls = msg["calls"]
                    last = len(calls) - 1
                    for i, sub in enumerate(calls):
                        if sub.get("t") == "dcancel":
                            # a cancel queued ahead of its (still
                            # unflushed) dcall rides the same frame
                            self._worker.cancel_registry.cancel(
                                sub["task_id"])
                            continue
                        self._handle_call(conn, sub,
                                          i < last or bool(reader._pending))
        finally:
            # notes record executions that HAPPENED — they must reach the
            # raylet even when the caller hangs up mid-train
            conn.flush_notes(self._worker)
            conn.alive = False
            try:
                conn.sock.close()
            except OSError:
                pass
            with self._conns_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass

    def close(self):
        for lsock in self._listeners:
            try:
                lsock.close()
            except OSError:
                pass
        try:
            os.unlink(self.unix_path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Caller side


class _Pending:
    """One in-flight direct call, resolved by the channel reader (result)
    or by teardown (fallback via the raylet path)."""

    __slots__ = ("event", "spec", "ctx", "t_sent", "fallback", "done")

    def __init__(self, spec: TaskSpec, ctx):
        # ``done`` is the resolution flag; ``event`` is allocated LAZILY,
        # only when a second thread actually parks on this entry — the
        # common burst case (one thread submits AND demuxes) never pays
        # the three allocations inside threading.Event().  Writers set
        # ``done`` BEFORE reading ``event``; a parking thread installs
        # ``event`` under the channel lock and re-checks ``done`` after,
        # so no wake-up can be lost (GIL-atomic attribute stores).
        self.event: Optional[threading.Event] = None
        self.done = False
        self.spec = spec
        self.ctx = ctx  # sampled trace ctx or None (unsampled fast path)
        self.t_sent = 0.0
        self.fallback = False

    def resolve(self):
        """Mark resolved and wake any parked waiter (done-then-event
        order pairs with _await's install-then-recheck)."""
        self.done = True
        ev = self.event
        if ev is not None:
            ev.set()


class _Channel:
    """A dialed caller→callee connection (one per actor or lease).

    No standing reader thread: the socket is demuxed by whichever caller
    thread is waiting in ``get()`` (``_await`` takes ``recv_lock`` and
    recv's until its own result lands, dispatching everyone else's on
    the way), so the result wakes the actual waiter straight out of the
    kernel — no reader→getter handoff, no idle thread churning the GIL.
    Fire-and-forget bursts stay deadlock-free because ``submit`` drains
    the socket opportunistically once enough calls are in flight, and a
    caller that neither gets nor submits leaves results in the kernel
    buffer — bounded by the callee's send timeout, after which the
    callee drops the conn and the raylet path (already notified via
    direct_done) serves the results."""

    def __init__(self, mgr: "DirectCallClient", key, info: dict):
        self.mgr = mgr
        self.key = key  # ActorID, or ("lease", shape) for task leases
        self.node_id = info.get("node_id")
        self.generation = info.get("generation", 0)
        self.lease_id = info.get("lease_id")
        self.lock = make_lock("direct.channel.state")
        self.send_lock = make_lock("direct.channel.send")
        self.recv_lock = make_lock("direct.channel.recv")
        # Serializes sendbuf-swap + wire write: two racing flushes must
        # hit the socket in swap order or per-handle FIFO breaks.
        self.flush_lock = make_lock("direct.channel.flush")
        self.pending: "OrderedDict[Any, _Pending]" = OrderedDict()  # guard: lock
        self.alive = True  # guard: lock
        # Observed per-call turnover (decode→result at the callee, EWMA
        # seconds) — burst mode pipelines a lease channel only below
        # direct_lease_turnover_ms (guard: lock)
        self.turnover_ewma: Optional[float] = None
        # Outbound dcall frames awaiting coalesced flush (guard: lock):
        # a burst of submits ships as ONE sendall — flushed inline at 16,
        # by the first get()'s resolve, or by the manager's micro-flusher
        # (sub-ms) for pure fire-and-forget, so a call can never sit
        # unsent indefinitely.
        self.sendbuf: List[dict] = []
        self.last_used = time.monotonic()
        self.sock = self._dial(info)
        self._reader = protocol.FrameReader(self.sock)  # guard: recv_lock

    def _dial(self, info: dict) -> socket.socket:
        addr = info["addr"]
        timeout = max(0.1, config.direct_connect_timeout_s)
        unix = addr.get("unix")
        sock = None
        if unix and addr.get("hostname") == socket.gethostname() \
                and os.path.exists(unix):
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(timeout)
            try:
                sock.connect(unix)
            except OSError:
                sock.close()
                sock = None
        if sock is None:
            tcp = addr.get("tcp")
            if not tcp:
                raise OSError("no dialable direct address")
            sock = socket.create_connection(tuple(tcp), timeout=timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            protocol.send_msg(sock, {
                "t": "dhello",
                "caller": self.mgr.worker_id_hex,
                "actor_id": self.key if not isinstance(self.key, tuple)
                else None,
                "generation": self.generation,
                "incarnation": info.get("incarnation", 0),
                "lease_id": self.lease_id,
            })
            ack = protocol.recv_msg(sock)
        except (OSError, protocol.ProtocolError):
            sock.close()
            raise OSError("direct hello failed")
        if not isinstance(ack, dict) or not ack.get("ok"):
            sock.close()
            reason = ack.get("reason") if isinstance(ack, dict) else "EOF"
            raise OSError(f"direct hello rejected: {reason}")
        sock.settimeout(None)
        return sock

    # ---- submit / results ----

    def submit(self, spec: TaskSpec, ctx) -> bool:
        """Ship one call, or return False to hand it to the relayed path.

        Burst mode (default): past ``direct_burst_window`` unacked calls
        the submitting thread demuxes the channel socket to advance the
        ack window — each dresult acks one slot — so a deep
        fire-and-forget burst pipelines over the one FIFO socket with
        ≤W in flight and never falls back mid-burst.

        Kill switch (RAY_TPU_DIRECT_BURST=0) restores the pre-burst
        behavior exactly: the direct channel is a LATENCY transport, and
        past direct_pipeline_depth in flight the window is drained as an
        ordering barrier and the burst handed back to the raylet, which
        out-runs a single submitting thread at depth.  Re-engagement
        (all completions observed) restores the direct path for the next
        call/response phase."""
        if config.direct_burst:
            if not self._advance_window(max(1, config.direct_burst_window)):
                return False
        else:
            cap = max(1, config.direct_pipeline_depth)
            with self.lock:
                over = self.alive and len(self.pending) >= cap
            if over:
                self._drain_all()
                return False
        entry = _Pending(spec, ctx)
        entry.t_sent = time.time()
        with self.lock:
            if not self.alive:
                return False
            self.pending[spec.task_id] = entry
            depth = len(self.pending)
            self.last_used = time.monotonic()
            self.sendbuf.append({"t": "dcall", "spec": spec})
            # half-window flush matches _advance_window's half-window
            # drain: a steady-state burst alternates one dburst frame of
            # W/2 calls with one demux round of W/2 acks
            flush_now = (depth == 1
                         or len(self.sendbuf)
                         >= max(1, config.direct_burst_window // 2))
        if flush_now:
            # an empty pipeline means a latency-sensitive caller (sync
            # call loop): put the frame on the wire NOW
            self.flush()
        else:
            # fire-and-forget: the manager's micro-flusher ships it if no
            # get()/follow-up submit does first
            self.mgr._arm_flusher()
        if ctx is not None:
            from ray_tpu.util import tracing

            tracing.hop("worker.direct_send", ctx, entry.t_sent,
                        time.time(), task_id=spec.task_id.hex())
        return True

    def _drain_all(self):
        """Ordering barrier: block until every in-flight direct call on
        this channel resolved, so a call relayed next cannot overtake
        one still queued at the callee."""
        while True:
            with self.lock:
                if not self.alive or not self.pending:
                    return
                oldest = next(iter(self.pending.values()))
            self._await(oldest, None)

    def _advance_window(self, cap: int) -> bool:
        """Windowed ack (burst mode): when ``cap`` calls are unacked,
        demux the socket on this very thread — each dresult is the ack —
        until the window is HALF empty, then resume submitting.  The
        half-window hysteresis is what makes coalescing work: draining
        just one slot per submit would interleave flush/demux with every
        call and put one frame per call on the wire; draining to cap/2
        lets the next cap/2 submits pile into the sendbuf and ship as a
        single dburst frame, which in turn arrives at the callee as a
        coalesced train (batched notes, batched result flush).  No
        per-call round trip, no relayed hand-back.  False = the channel
        died while advancing (the caller relays, and teardown has
        already reconciled the window)."""
        with self.lock:
            if not self.alive:
                return False
            if len(self.pending) < cap:
                return True
        target = max(cap // 2, 1)
        while True:
            with self.lock:
                if not self.alive:
                    return False
                if len(self.pending) < target:
                    return True
                oldest = next(iter(self.pending.values()))
            self._await(oldest, None)

    def poll(self):
        """Opportunistic non-blocking demux: drain any dresults already
        decoded or sitting in the kernel buffer, without waiting.  Lets
        a fan-out loop that is still relaying (lease turnover unknown)
        observe completions — and their dur stamps — so burst
        pipelining can engage mid-loop."""
        if not self.recv_lock.acquire(blocking=False):
            return  # another thread is demuxing already
        try:
            while True:
                if not self._reader._pending:  # unguarded-ok: recv_lock IS held — manual try-acquire above, invisible to the lexical pass
                    try:
                        ready, _, _ = select.select([self.sock], [], [], 0)
                    except (OSError, ValueError):
                        return  # socket closed under us: teardown owns it
                    if not ready:
                        return
                try:
                    msg = self._reader.recv_msg()  # unguarded-ok: recv_lock IS held — manual try-acquire above, invisible to the lexical pass
                except (OSError, protocol.ProtocolError):
                    msg = None
                if msg is None:
                    self.teardown("connection closed")
                    return
                if not self._dispatch(msg):
                    return
        finally:
            self.recv_lock.release()

    def flush(self):
        if not self.sendbuf:  # unguarded-ok: GIL-atomic emptiness peek; the locked re-check below decides
            # fast path: _await/_advance_window call flush once per
            # demuxed entry — a burst drain would otherwise pay two lock
            # rounds per ack just to discover there is nothing to send
            return
        # flush_lock spans swap + write: a racing pair of flushes (micro-
        # flusher vs. a get()'s _await) must reach the wire in swap order
        # or per-handle FIFO breaks
        with self.flush_lock:
            with self.lock:
                if not self.sendbuf:
                    return
                out, self.sendbuf = self.sendbuf, []
            if len(out) > 1 and config.direct_burst:
                # one dburst frame per flush window: pickling the specs
                # together memoizes shared strings (function/module
                # names, resource keys) across the burst instead of
                # paying them per call
                out = [{"t": "dburst", "calls": out}]
            try:
                protocol.send_msgs(self.sock, out, self.send_lock)
            except OSError:
                self.teardown("send failed")  # reconciles every pending call

    def idle(self) -> bool:
        with self.lock:
            return not self.pending

    # ---- demux (runs on whichever thread needs a result) ----

    def _dispatch(self, msg: dict) -> bool:
        """Handle one inbound frame; False = channel torn down."""
        if msg.get("t") != "dresult":
            return True
        if msg.get("rejected"):
            # callee refused (freeze gate / stale conn): everything in
            # flight reconciles via the raylet, which dedups/fences
            self.teardown("rejected by callee")
            return False
        with self.lock:
            entry = self.pending.pop(msg["task_id"], None)
            self.last_used = time.monotonic()
            dur = msg.get("dur")
            if dur is not None:
                # callee-stamped decode→result turnover: the evidence the
                # lease-pipelining gate (_fast_turnover) runs on
                ew = self.turnover_ewma
                self.turnover_ewma = dur if ew is None \
                    else ew * 0.8 + dur * 0.2
        if entry is None:
            return True
        spec = entry.spec
        mgr = self.mgr
        results = {}
        if msg["ok"]:
            for h, blob in (msg.get("inline") or {}).items():
                results[h] = ("inline", blob)
            for h in (msg.get("stored") or ()):
                results[h] = ("store",)
        else:
            err = msg.get("error")
            for oid in spec.return_ids():
                results[oid.hex()] = ("error", err)
        mgr._store_results(results)
        entry.resolve()
        mgr._release_inner_refs(spec)
        if entry.ctx is not None:
            from ray_tpu.util import tracing

            now = time.time()
            tracing.hop("worker.direct_result", entry.ctx,
                        max(entry.t_sent, now - 1e-6), now,
                        task_id=spec.task_id.hex())
        return True

    def _await(self, entry: _Pending, deadline: Optional[float]):
        """Block until ``entry`` resolves: the first waiter becomes the
        channel's demultiplexer (recv's straight off the socket —
        results wake the real waiter out of the kernel, no reader-thread
        handoff); others park on their event and re-bid for the recv
        lock on a short period."""
        from ray_tpu.core.exceptions import GetTimeoutError

        self.flush()  # anything still coalescing must be on the wire
        while not entry.done:
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(
                    "get() timed out waiting on a direct call")
            if not self.recv_lock.acquire(blocking=False):
                # someone else demuxes; they will wake us.  Install the
                # entry's (lazy) event first, then re-check done — the
                # resolver sets done BEFORE reading event, so this order
                # cannot miss the wake-up.
                ev = entry.event
                if ev is None:
                    with self.lock:
                        ev = entry.event
                        if ev is None:
                            entry.event = ev = threading.Event()
                    if entry.done:
                        continue
                ev.wait(0.02)
                continue
            try:
                while not entry.done:
                    if not self._reader._pending:  # unguarded-ok: recv_lock IS held — manual try-acquire above, invisible to the lexical pass
                        # only hit the kernel when the reader's decoded
                        # backlog is empty — a chunked recv decodes many
                        # results at once and select() knows nothing
                        # about them
                        if deadline is not None:
                            budget = deadline - time.monotonic()
                            if budget <= 0:
                                raise GetTimeoutError(
                                    "get() timed out waiting on a direct "
                                    "call")
                        else:
                            budget = None
                        # bounded block so a teardown (fence) or deadline
                        # is noticed even if the socket close loses the
                        # race with our select()
                        try:
                            ready, _, _ = select.select(
                                [self.sock], [], [],
                                1.0 if budget is None else min(1.0, budget))
                        except (OSError, ValueError):
                            ready = None  # socket closed under us
                        with self.lock:
                            alive = self.alive
                        if not alive:
                            return  # teardown resolved every pending entry
                        if ready is None:
                            self.teardown("connection closed")
                            return
                        if not ready:
                            continue
                    try:
                        msg = self._reader.recv_msg()  # unguarded-ok: recv_lock IS held — manual try-acquire above, invisible to the lexical pass
                    except (OSError, protocol.ProtocolError):
                        msg = None
                    if msg is None:
                        self.teardown("connection closed")
                        return
                    if not self._dispatch(msg):
                        return
            finally:
                self.recv_lock.release()

    # ---- failure handling ----

    def teardown(self, reason: str):
        """Kill the channel and reconcile in-flight calls via the raylet
        path: each pending spec is resubmitted with ``_direct_retry`` —
        already-delivered results are skipped raylet-side, a restarted
        actor fences on the generation (retryable ActorDiedError), and a
        live same-generation actor re-runs at most once, deduped by the
        callee's executed-result cache."""
        with self.lock:
            if not self.alive:
                return
            self.alive = False
            drain = list(self.pending.values())
            self.pending.clear()
            self.sendbuf = []  # unsent calls reconcile like sent ones
        try:
            # shutdown (not just close) wakes any demuxer blocked in
            # select/recv on another thread
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        mgr = self.mgr
        mgr._drop_channel(self)
        if self.lease_id is not None:
            mgr._release_lease(self)
        for entry in drain:
            spec = entry.spec
            spec._direct_retry = True
            spec._direct_generation = self.generation
            entry.fallback = True
            try:
                mgr._resubmit(spec)
            except Exception:  # noqa: BLE001 — shutdown races
                pass
            # the reconcile rides the relayed path: arm the engagement
            # watermark so a re-dialed channel (same or bumped
            # generation) cannot overtake a partially-acked window —
            # the direct path re-engages only after these are observed
            # delivered (no-op for lease/normal specs)
            mgr._note_relayed(spec)
            entry.resolve()
            mgr._release_inner_refs(spec)


class DirectCallClient:
    """Caller-side direct transport: channel cache + pending table +
    raylet-path fallback, shared by drivers (in-process raylet), remote
    drivers, and worker processes (their adapters differ, the state
    machine doesn't)."""

    def __init__(self, worker, broker: Callable[[Any], Optional[dict]],
                 resubmit: Callable[[TaskSpec], None],
                 lease: Optional[Callable[[TaskSpec], Optional[dict]]] = None,
                 lease_release: Optional[Callable[[str], None]] = None):
        self._worker = worker
        self.worker_id_hex = worker.worker_id.hex()
        self._broker = broker
        self._resubmit = resubmit
        self._lease = lease
        self._lease_release = lease_release
        self._lock = make_lock("direct.client")
        self._channels: Dict[Any, _Channel] = {}  # guard: _lock
        # Per-actor engagement state (guard: _lock): switching to the
        # direct path is order-safe once every previously relayed call
        # has been DELIVERED to the worker.  Relay delivery is FIFO per
        # caller (driver → raylet inbox → actor queue → socket), so one
        # WATERMARK per actor suffices: the last relayed call's return
        # oid — observing its (non-error) result implies every earlier
        # relayed call was delivered.  O(1) state per actor; a
        # fire-and-forget burst of any size re-engages after one get.
        self._actors: Dict[Any, dict] = {}
        # watermark return-oid hex -> actor_id (one live entry per actor)
        self._last_relayed: Dict[str, Any] = {}
        self._results: "OrderedDict[str, tuple]" = OrderedDict()
        self._closed = False
        self._sweeper_started = False
        # send-coalescing micro-flusher (lazy): ships buffered dcalls a
        # few hundred µs after a fire-and-forget submit if no get() or
        # follow-up submit flushed them first
        self._flush_event = threading.Event()
        self._flusher_started = False

    # ------------------------------------------------------------- submit

    def try_submit(self, spec: TaskSpec) -> bool:
        """True = the call rides (or was reconciled through) the direct
        path and must NOT be relayed by the caller; False = relay."""
        if self._closed or not config.direct_calls:
            # still record the watermark: if the kill switch is flipped
            # back on, a surviving channel must not re-engage until these
            # relayed calls are observed delivered (per-handle FIFO)
            self._note_relayed(spec)
            return False
        if spec.kind == ACTOR_TASK:
            return self._submit_actor(spec)
        if spec.kind == NORMAL_TASK and self._lease is not None:
            return self._submit_task(spec)
        return False

    def _eligible_actor_call(self, spec: TaskSpec) -> bool:
        return (spec.num_returns != STREAMING_RETURNS
                and spec.method_name != "__ray_terminate__"
                and not spec.dependency_ids())

    def _submit_actor(self, spec: TaskSpec) -> bool:
        aid = spec.actor_id
        if aid is None or not self._eligible_actor_call(spec):
            self._note_relayed(spec)
            return False
        ch = self._channels.get(aid)  # unguarded-ok: GIL-atomic probe, re-checked under the channel lock in submit()
        if ch is None or not ch.alive:
            ch = self._maybe_engage(aid)
            if ch is None:
                self._note_relayed(spec)
                return False
        else:
            st = self._actors.get(aid)
            if st is not None and st["last"] is not None:  # unguarded-ok: GIL-atomic read; a stale watermark just relays one more call
                # earlier calls took the relayed path (deep-burst
                # hand-back) and their delivery is not yet confirmed:
                # relaying this one too preserves per-handle order
                self._note_relayed(spec)
                return False
        self._pin_inner_refs(spec)
        if ch.submit(spec, _trace_ctx(spec)):
            return True
        # teardown race or window-full hand-back: relay
        self._release_inner_refs(spec)
        self._note_relayed(spec)
        return False

    def _maybe_engage(self, aid) -> Optional[_Channel]:
        """Broker + dial a direct channel for an actor — only once every
        previously relayed call has been observed complete (per-handle
        FIFO order survives the switch) and outside any backoff window."""
        now = time.monotonic()
        with self._lock:
            st = self._actors.get(aid)
            if st is None or st["last"] is not None or st["completed"] == 0:
                return None
            if now < st["next_try"]:
                return None
            ch = self._channels.get(aid)
            if ch is not None and ch.alive:
                return ch
            st["next_try"] = now + 0.25  # armed before the blocking dial
        try:
            info = self._broker(aid)
        except Exception:  # noqa: BLE001 — raylet busy/shutdown: relay
            info = None
        if not info:
            return None
        try:
            ch = _Channel(self, aid, info)
        except _DIAL_ERRORS:
            return None
        with self._lock:
            cur = self._channels.get(aid)
            if cur is not None and cur.alive:
                dup = ch
                ch = cur
            else:
                self._channels[aid] = ch
                dup = None
        if dup is not None:
            try:
                dup.sock.close()
            except OSError:
                pass
        return ch

    # ---- lease-reused normal tasks ----

    def _eligible_task(self, spec: TaskSpec) -> bool:
        return (spec.num_returns == 1
                and not spec.dependency_ids()
                and not spec.placement
                and spec.runtime_env is None
                and not spec.retry_exceptions)

    def _submit_task(self, spec: TaskSpec) -> bool:
        if not self._eligible_task(spec):
            return False
        key = ("lease", tuple(sorted((spec.resources or {}).items())))
        ch = self._channels.get(key)  # unguarded-ok: GIL-atomic probe, re-checked under the channel lock in submit()
        if ch is None or not ch.alive:
            ch = self._maybe_lease(key, spec)
            if ch is None:
                return False
        # Serial reuse by default: a fan-out must spread over the pool,
        # not serialize onto one leased worker — the lease accelerates
        # call→result→call loops, the raylet keeps everything parallel.
        # Burst mode pipelines PROVEN fast-turnover channels (EWMA below
        # direct_lease_turnover_ms, stamped by the callee per dresult):
        # sub-ms tasks gain more from pipelined submission than from
        # per-task raylet dispatch, while unknown or slow channels keep
        # the spread.
        if not ch.idle():
            if not (config.direct_burst and self._fast_turnover(ch)):
                ch.poll()  # gather turnover evidence without blocking
                return False
        self._pin_inner_refs(spec)
        if ch.submit(spec, _trace_ctx(spec)):
            return True
        self._release_inner_refs(spec)
        return False

    def _fast_turnover(self, ch: _Channel) -> bool:
        ew = ch.turnover_ewma  # unguarded-ok: GIL-atomic read; staleness costs at most one relayed call
        return (ew is not None
                and ew * 1000.0 <= config.direct_lease_turnover_ms)

    def _maybe_lease(self, key, spec: TaskSpec) -> Optional[_Channel]:
        now = time.monotonic()
        with self._lock:
            st = self._actors.setdefault(key, {"last": None, "completed": 1,
                                               "next_try": 0.0})
            if now < st["next_try"]:
                return None
            ch = self._channels.get(key)
            if ch is not None and ch.alive:
                return ch
            st["next_try"] = now + 0.25
        try:
            info = self._lease(spec)
        except Exception:  # noqa: BLE001
            info = None
        if not info:
            return None
        try:
            ch = _Channel(self, key, info)
        except _DIAL_ERRORS:
            # the worker never saw a usable channel: hand the lease back
            if self._lease_release is not None:
                try:
                    self._lease_release(info["lease_id"])
                except Exception:  # noqa: BLE001
                    pass
            return None
        with self._lock:
            self._channels[key] = ch
            need_sweeper = not self._sweeper_started
            self._sweeper_started = True
        if need_sweeper:
            threading.Thread(target=self._lease_sweep_loop,
                             name="direct-lease-sweep", daemon=True).start()
        return ch

    def _lease_sweep_loop(self):
        """Return idle leases to the pool so a quiet caller never holds a
        worker (and its resources) beyond direct_lease_idle_s."""
        while not self._closed:
            time.sleep(max(0.2, config.direct_lease_idle_s / 2))
            now = time.monotonic()
            with self._lock:
                idle = [ch for ch in self._channels.values()
                        if ch.lease_id is not None and ch.alive
                        and not ch.pending
                        and now - ch.last_used > config.direct_lease_idle_s]
            for ch in idle:
                with ch.lock:
                    if ch.pending or not ch.alive:
                        continue
                    ch.alive = False
                try:
                    ch.sock.close()
                except OSError:
                    pass
                self._drop_channel(ch)
                self._release_lease(ch)

    def _arm_flusher(self):
        if not self._flusher_started:
            with self._lock:
                if not self._flusher_started:
                    self._flusher_started = True
                    threading.Thread(target=self._send_flush_loop,
                                     name="direct-send-flush",
                                     daemon=True).start()
        ev = self._flush_event
        if not ev.is_set():
            # skip the condition-variable round when already armed — a
            # burst re-arms once per flusher wake-up, not once per submit
            ev.set()

    def _send_flush_loop(self):
        while not self._closed:
            self._flush_event.wait()
            self._flush_event.clear()
            time.sleep(0.0005)  # let a submit burst coalesce
            for ch in list(self._channels.values()):  # unguarded-ok: snapshot; flush() re-checks under the channel lock
                ch.flush()

    def _release_lease(self, ch: _Channel):
        if self._lease_release is None or ch.lease_id is None:
            return
        try:
            self._lease_release(ch.lease_id)
        except Exception:  # noqa: BLE001 — raylet gone / worker death raced
            pass

    # ------------------------------------------------------- bookkeeping

    def _pin_inner_refs(self, spec: TaskSpec):
        """Process-level holds for refs serialized inside inline args: the
        relayed path pins them raylet-side at submit; the direct path
        must keep them alive itself until the call completes (the hold
        events ride the ordinary ref-event stream, ordered ahead of any
        later release by this process)."""
        if not spec.inner_refs:
            return
        from ray_tpu.core.worker import note_refs_created

        note_refs_created(spec.inner_refs)  # one lock round per submit

    def _release_inner_refs(self, spec: TaskSpec):
        if not spec.inner_refs or getattr(spec, "_inner_released", False):
            return
        spec._inner_released = True
        from ray_tpu.core.worker import note_refs_dropped

        note_refs_dropped(spec.inner_refs)

    def _store_results(self, results: Dict[str, tuple]):
        with self._lock:
            self._results.update(results)
            while len(self._results) > config.direct_result_cache:
                self._results.popitem(last=False)

    def _drop_channel(self, ch: _Channel):
        with self._lock:
            if self._channels.get(ch.key) is ch:
                del self._channels[ch.key]

    def _note_relayed(self, spec: TaskSpec):
        if spec.kind != ACTOR_TASK or spec.actor_id is None:
            return
        with self._lock:
            st = self._actors.setdefault(
                spec.actor_id, {"last": None, "completed": 0,
                                "next_try": 0.0})
            prev = st["last"]
            if prev is not None:
                self._last_relayed.pop(prev, None)
            h = spec.return_ids()[0].hex()
            st["last"] = h
            self._last_relayed[h] = spec.actor_id

    def note_observed(self, oids, errored=None):
        """Called by get()/wait() when results are observed resolved.
        Observing the watermark (the LAST relayed call) clears the
        actor's relayed backlog: FIFO relay delivery means everything
        before it reached the worker, so switching to the direct path
        is order-safe.  An ERRORED watermark does not clear — a call
        failed at the raylet (dep error, dead actor) proves nothing
        about the delivery of its predecessors."""
        if not self._last_relayed:  # unguarded-ok: GIL-atomic emptiness probe; a miss only delays engagement one get
            return
        with self._lock:
            for oid in oids:
                h = oid.hex()
                aid = self._last_relayed.get(h)
                if aid is None:
                    continue
                if errored is not None and h in errored:
                    continue
                del self._last_relayed[h]
                st = self._actors.get(aid)
                if st is not None and st["last"] == h:
                    st["last"] = None
                    st["completed"] += 1

    # ------------------------------------------------------------- get()

    def resolve(self, oid, deadline: Optional[float]):
        """Resolve a direct-call return: a cached result tuple
        (("inline", blob) / ("error", err) / ("store",)), or None when
        the oid is unknown here or fell back to the raylet path.  Blocks
        while the call is in flight; raises GetTimeoutError past the
        deadline."""
        if not self._channels and not self._results:  # unguarded-ok: GIL-atomic emptiness probes (fast path for non-direct gets)
            return None
        h = oid.hex()
        # pop, don't peek: a delivered result is consumed exactly once
        # (a re-get falls back to the raylet path, where the callee's
        # direct_done already registered it) — otherwise a burst larger
        # than direct_result_cache evicts results the caller has not
        # read yet and every evictee pays a raylet round trip
        with self._lock:
            r = self._results.pop(h, None)
        if r is not None:
            return r
        tid = oid.task_id()
        entry = owner = None
        for ch in list(self._channels.values()):  # unguarded-ok: snapshot; a racing teardown resolves the entry anyway
            with ch.lock:
                entry = ch.pending.get(tid)
            if entry is not None:
                owner = ch
                break
        if entry is None:
            return None
        owner._await(entry, deadline)  # this thread demuxes the socket
        with self._lock:
            return self._results.pop(h, None)  # None => reconciled via raylet

    # ------------------------------------------------------------- cancel

    def cancel(self, oid) -> bool:
        """Cancel fan-out over the direct transport: if the call that
        produces ``oid`` is in flight on a dialed channel, ship a dcancel
        frame to the callee (its in-flight registry interrupts or
        pre-exec-fails the call; the ordinary dresult/raylet bookkeeping
        then carries the typed TaskCancelledError back).  Returns True
        when a channel had the call in flight."""
        tid = oid.task_id()
        for ch in list(self._channels.values()):  # unguarded-ok: snapshot; a racing teardown reconciles the call anyway
            queued = False
            with ch.lock:
                if tid not in ch.pending or not ch.alive:
                    continue
                for i, frame in enumerate(ch.sendbuf):
                    if frame.get("t") == "dcall" \
                            and frame["spec"].task_id == tid:
                        # the dcall is still coalescing in the burst
                        # buffer: queue the cancel IN FRONT of it, so the
                        # callee's registry marks the task before its
                        # pre-exec check ever runs
                        ch.sendbuf.insert(i, {"t": "dcancel",
                                              "task_id": tid})
                        queued = True
                        break
            ch.flush()  # the dcall itself must not sit behind the cancel
            if queued:
                return True
            try:
                protocol.send_msg(ch.sock, {"t": "dcancel", "task_id": tid},
                                  ch.send_lock)
            except OSError:
                ch.teardown("send failed")
                return False
            return True
        return False

    # ------------------------------------------------------------- fences

    def on_fence(self, msg: dict):
        """Raylet notice: an actor died/restarted or a node went
        SUSPECT/DEAD — tear down matching channels now so blocked
        callers reconcile instead of waiting out a partition."""
        actor_ids = set(msg.get("actor_ids") or ())
        node_id = msg.get("node_id")
        with self._lock:
            victims = [ch for ch in self._channels.values()
                       if ch.key in actor_ids
                       or (node_id is not None and ch.node_id == node_id)]
        for ch in victims:
            ch.teardown("fenced by raylet")

    def forget_actor(self, actor_id):
        """Proactive teardown on ray_tpu.kill(): the kill travels the
        raylet path; direct frames must not race it."""
        ch = self._channels.get(actor_id)  # unguarded-ok: GIL-atomic probe; teardown re-checks under the channel lock
        if ch is not None:
            ch.teardown("actor killed")

    def close(self):
        self._closed = True
        self._flush_event.set()  # let the micro-flusher exit
        with self._lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            with ch.lock:
                ch.alive = False
                drain = list(ch.pending.values())
                ch.pending.clear()
            try:
                ch.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                ch.sock.close()
            except OSError:
                pass
            for entry in drain:
                entry.resolve()
            if ch.lease_id is not None:
                self._release_lease(ch)
