"""ClientWorker — a driver connected to a cluster over TCP.

Reference analogue: a driver's ``CoreWorker`` connecting to its local raylet
(`python/ray/_private/worker.py:2020` → `ConnectToRaylet`,
`src/ray/core_worker/core_worker.h:313`) — here the driver speaks the same
framed request protocol the workers use, to the raylet's TCP listener, and
holds a ``GcsClient`` for cluster-level queries.  When the raylet is on the
same host the driver attaches its shm store for zero-copy gets; otherwise
large objects would need a socket fetch (not yet wired — same-host only).
"""

from __future__ import annotations

import socket
import sys
import threading
import time
from typing import Dict, Optional

from ray_tpu.core import protocol
from ray_tpu.core.config import config
from ray_tpu.core.gcs import GcsClient
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.worker import Worker
from ray_tpu.util.locks import make_lock
from ray_tpu.util.retry import BackoffPolicy

config.define("gcs_client_reconnect_attempts", int, 4,
              "Driver-side GCS reconnect: how many re-dial attempts a "
              "GCS op gets after its connection drops (a GCS restart "
              "leaves the old socket dead while the service comes back), "
              "spaced by the jittered RAY_TPU_RETRY_BACKOFF_* policy so "
              "many drivers don't re-dial a restarting GCS in lockstep.")


class ClientWorker(Worker):
    """Driver-side connection to a raylet over TCP ("client" mode)."""

    def __init__(self, gcs_address: str, node_id: Optional[str] = None,
                 log_to_driver: bool = True):
        super().__init__("client")
        self.log_to_driver = log_to_driver
        self._gcs_address = gcs_address
        self.gcs = GcsClient(gcs_address)
        nodes = [n for n in self.gcs.nodes() if n["alive"] and n["address"]]
        if not nodes:
            raise ConnectionError(f"no alive nodes registered at {gcs_address}")
        if node_id is not None:
            nodes = [n for n in nodes if n["node_id"] == node_id]
            if not nodes:
                raise ValueError(f"node {node_id} not found/alive")
        # prefer a raylet on this host (store attach works there)
        hostname = socket.gethostname()
        nodes.sort(key=lambda n: (n.get("hostname") != hostname,))
        info = nodes[0]
        host, port = info["address"]
        self.sock = socket.create_connection((host, port), timeout=10)
        self.sock.settimeout(None)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.send_lock = make_lock("client.send")
        self._rid = 0  # guard: _rid_lock
        self._rid_lock = make_lock("client.rid")
        self._pending: Dict[int, dict] = {}
        self._hello = threading.Event()
        self._hello_msg: Optional[dict] = None
        self._reader = threading.Thread(target=self._read_loop,
                                        name="client-reader", daemon=True)
        self._reader.start()
        self._send({"t": "driver_hello"})
        if not self._hello.wait(10):
            raise ConnectionError("raylet handshake timed out")
        self.node_id = self._hello_msg["node_id"]
        self.session_dir = self._hello_msg["session_dir"]
        store_path = self._hello_msg.get("store_path")
        if store_path:
            try:
                self.store = ShmObjectStore(store_path)
            except OSError:
                self.store = None  # different host: no shm access
        from ray_tpu.util import profiling, tracing

        tracing.maybe_enable_from_env()
        if tracing.tracing_enabled():
            # ship this driver's spans (task.submit / task.get / serve
            # hops) to the raylet like a worker does — the raylet batches
            # them into the GCS trace table
            tracing.set_flush_target(
                lambda spans, dropped: self._send(
                    {"t": "spans", "spans": spans, "dropped": dropped}))
        # continuous profiling of the driver process: folded samples ride
        # the same worker route (raylet -> GCS profile table)
        profiling.ensure_profiler("driver")
        profiling.set_flush_target(
            lambda samples, dropped: self._send(
                {"t": "profile_samples", "samples": samples,
                 "dropped": dropped}))
        # metric time-series delta points ride it too (raylet -> GCS
        # metrics table); registered unconditionally — the per-process
        # flusher only runs once a metric is registered, and checks the
        # metrics_history flag itself
        from ray_tpu.util import metrics as _metrics_mod

        _metrics_mod.set_points_target(
            lambda points, dropped: self._send(
                {"t": "metric_points", "points": points,
                 "dropped": dropped}))
        # Direct worker→worker transport (remote-driver caller side): the
        # raylet brokers actor addresses / worker leases over the request
        # protocol; direct_fence control frames arrive on the read loop.
        from ray_tpu.core.config import config as _config

        if _config.direct_calls:
            from ray_tpu.core.direct import DirectCallClient

            # broker/lease round trips are bounded like the in-process
            # driver's (.result(2.0)): a stalled raylet must cost the
            # submit path one timeout and a relayed fallback, never a
            # wedged burst
            self._direct = DirectCallClient(
                self,
                broker=lambda aid: self._request("direct_lookup",
                                                 actor_id=aid,
                                                 _wait_timeout=2.0),
                resubmit=self._submit_relayed,
                lease=lambda spec: self._request("direct_lease", spec=spec,
                                                 _wait_timeout=2.0),
                lease_release=lambda lid: self._request(
                    "direct_lease_release", lease_id=lid,
                    _wait_timeout=2.0),
            )

    # Worker.get/put/wait/submit use _send/_request like worker mode does.

    def _read_loop(self):
        reader = protocol.FrameReader(self.sock)
        while True:
            try:
                msg = reader.recv_msg()
            except (OSError, protocol.ProtocolError):
                msg = None
            if msg is None:
                err = ConnectionError("raylet connection lost")
                for entry in list(self._pending.values()):
                    entry["msg"] = {"ok": False, "error": err}
                    entry["event"].set()
                return
            t = msg.get("t")
            if t == "hello_reply":
                self._hello_msg = msg
                self._hello.set()
            elif t == "reply":
                entry = self._pending.pop(msg["rid"], None)
                if entry is not None:
                    entry["msg"] = msg
                    entry["event"].set()
            elif t == "direct_fence":
                if self._direct is not None:
                    self._direct.on_fence(msg)
            elif t == "log":
                # Worker stdout/stderr tailed by the raylet (reference: the
                # LogMonitor → driver console path, `log_monitor.py:102`).
                if self.log_to_driver:
                    prefix = (f"({msg.get('pid')}, "
                              f"node={str(msg.get('node_id'))[:8]}) ")
                    out = "".join(prefix + ln + "\n"
                                  for ln in msg.get("lines", ()))
                    try:
                        sys.stdout.write(out)
                        sys.stdout.flush()
                    except (OSError, ValueError):
                        pass

    def _send(self, msg):
        protocol.send_msg(self.sock, msg, self.send_lock)

    def _request(self, op, _wait_timeout=None, **fields):
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        entry = {"event": threading.Event(), "msg": None}
        self._pending[rid] = entry
        self._send({"t": "request", "rid": rid, "op": op, **fields})
        if not entry["event"].wait(_wait_timeout):
            self._pending.pop(rid, None)
            self._send({"t": "request", "rid": rid + (1 << 62),
                        "op": "cancel_request", "target_rid": rid})
            raise TimeoutError(f"request {op} timed out")
        msg = entry["msg"]
        if not msg["ok"]:
            raise msg["error"]
        return msg["value"]

    def _gcs_call(self, op, *args):
        """GCS ops with reconnect retries — after a GCS restart (fault
        tolerance) the old socket is dead but the service comes back
        within a few seconds.  Re-dials ride the unified jittered backoff
        policy: a fleet of drivers (or one driver fanning many threads
        into this path) spreads its re-dials instead of hammering the
        port the instant it reopens."""
        try:
            return getattr(self.gcs, op)(*args)
        except (ConnectionError, TimeoutError, OSError):
            pass
        policy = BackoffPolicy()
        attempts = max(1, config.gcs_client_reconnect_attempts)
        for attempt in range(attempts):
            try:
                new = GcsClient(self._gcs_address)
            except (ConnectionError, TimeoutError, OSError):
                if attempt == attempts - 1:
                    raise
                time.sleep(policy.delay(attempt))
                continue
            old, self.gcs = self.gcs, new
            try:
                old.close()
            except Exception:  # noqa: BLE001
                pass
            return getattr(self.gcs, op)(*args)

    def gcs_nodes(self):
        return self._gcs_call("nodes")

    def kv_put(self, key: bytes, value: bytes, namespace: str = ""):
        self._gcs_call("kv_put", namespace, key, value)

    def kv_get(self, key: bytes, namespace: str = ""):
        return self._gcs_call("kv_get", namespace, key)

    def kv_del(self, key: bytes, namespace: str = ""):
        return self._gcs_call("kv_del", namespace, key)

    def kv_keys(self, prefix: bytes, namespace: str = ""):
        return self._gcs_call("kv_keys", namespace, prefix)

    def _push_function(self, fid, blob: bytes):
        self._gcs_call("put_function", fid.binary(), blob)

    def shutdown(self):
        if self._direct is not None:
            self._direct.close()  # hand leases back before disconnecting
            self._direct = None
        try:
            self.sock.close()
        except OSError:
            pass
        try:
            self.gcs.close()
        except Exception:  # noqa: BLE001
            pass
        if self.store is not None:
            try:
                self.store.close()
            except Exception:  # noqa: BLE001
                pass
