"""Object serialization: pickle protocol 5 with out-of-band buffers.

Mirrors the reference's serialization design
(`python/ray/_private/serialization.py:398` — msgpack envelope + pickle5 with
zero-copy buffer callbacks): large contiguous buffers (numpy arrays, bytes,
jax host arrays) are split out of the pickle stream so that, when an object is
read from the shared-memory store, numpy views can alias the mmap directly
with no copy.

Wire format (little-endian):

    [u32 magic][u32 n_buffers][u64 pickled_len]
    [u64 buf_len * n_buffers]
    [pickled bytes]
    [padding to 64] [buffer 0] [padding to 64] [buffer 1] ...

Each buffer is aligned to 64 bytes so XLA/numpy get aligned host memory.

Device arrays: ``jax.Array`` values are converted to host numpy on serialize
(the object plane is host memory by design — device-to-device tensors move
via collectives, not the object store; see SURVEY.md §2.6 "Object plane").
"""

from __future__ import annotations

import pickle
import struct

import numpy as np
from typing import Any, List

_MAGIC = 0x52545055  # "RTPU"
_ALIGN = 64
_HEADER = struct.Struct("<IIQ")


class SerializedObject:
    """A serialized object as (meta, list of zero-copy buffers)."""

    __slots__ = ("pickled", "buffers")

    def __init__(self, pickled: bytes, buffers: List[memoryview]):
        self.pickled = pickled
        self.buffers = buffers

    def total_bytes(self) -> int:
        size = _HEADER.size + 8 * len(self.buffers) + len(self.pickled)
        size = _aligned(size)
        for b in self.buffers:
            size = _aligned(size + b.nbytes)
        return size

    def write_into(self, dest: memoryview) -> int:
        """Serialize into a writable buffer; returns bytes written."""
        n = len(self.buffers)
        _HEADER.pack_into(dest, 0, _MAGIC, n, len(self.pickled))
        off = _HEADER.size
        for b in self.buffers:
            struct.pack_into("<Q", dest, off, b.nbytes)
            off += 8
        dest[off : off + len(self.pickled)] = self.pickled
        off = _aligned(off + len(self.pickled))
        for b in self.buffers:
            flat = b.cast("B") if b.ndim != 1 or b.format != "B" else b
            if flat.nbytes >= (1 << 16):
                # numpy's copy loop runs ~3x faster than memoryview slice
                # assignment for large transfers (vectorized memcpy);
                # measured 2.25 -> 6.6 GiB/s host-bandwidth on v5e hosts.
                np.copyto(
                    np.frombuffer(dest, np.uint8, flat.nbytes, off),
                    np.frombuffer(flat, np.uint8))
            else:
                dest[off : off + flat.nbytes] = flat
            off = _aligned(off + flat.nbytes)
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_bytes())
        self.write_into(memoryview(out))
        return bytes(out)


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _device_to_host(obj: Any) -> Any:
    # Imported lazily: the core runtime must not require jax.
    try:
        import jax
        import numpy as np
    except ImportError:
        return obj
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    return obj


# Exact types whose pickle-5 stream is identical under stdlib pickle and
# cloudpickle, never triggers the out-of-band buffer callback, and needs
# no device-to-host conversion: the C ``pickle.dumps`` skips cloudpickle's
# per-call Pickler construction (~10µs), which dominates serializing the
# small scalar results the direct-transport hot path returns.
_FAST_TYPES = frozenset((bytes, str, int, float, bool, type(None)))


def serialize(obj: Any) -> SerializedObject:
    if type(obj) in _FAST_TYPES:
        return SerializedObject(pickle.dumps(obj, protocol=5), [])
    buffers: List[memoryview] = []

    def callback(pb: pickle.PickleBuffer) -> bool:
        raw = pb.raw()
        buffers.append(raw)
        return False  # out-of-band

    obj = _device_to_host(obj)
    # cloudpickle, not stdlib pickle: user scripts pass functions/classes
    # defined in __main__ or locally (train loops, actor classes) — stdlib
    # pickle serializes those BY REFERENCE (module+qualname), which silently
    # "succeeds" and then fails to resolve inside the worker process.
    # cloudpickle pickles them by value and delegates everything else to the
    # stdlib machinery (same protocol-5 out-of-band buffer handling).
    import cloudpickle

    pickled = cloudpickle.dumps(obj, protocol=5, buffer_callback=callback)
    return SerializedObject(pickled, buffers)


def serialize_with_refs(obj: Any):
    """serialize() + the ObjectIDs of every ObjectRef pickled inside the
    value — callers pin those ids for the serialized bytes' lifetime (the
    borrow-pinning protocol; see object_ref.collect_serialized_refs)."""
    if type(obj) in _FAST_TYPES:
        # no ObjectRef can hide inside a scalar/bytes value: skip the
        # collector context (a contextvar round per result otherwise)
        return serialize(obj), []
    from ray_tpu.core.object_ref import collect_serialized_refs

    with collect_serialized_refs() as c:
        ser = serialize(obj)
    return ser, c.ids


def deserialize(data: memoryview) -> Any:
    magic, n, plen = _HEADER.unpack_from(data, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt serialized object (bad magic)")
    off = _HEADER.size
    lens = []
    for _ in range(n):
        (l,) = struct.unpack_from("<Q", data, off)
        lens.append(l)
        off += 8
    # No bytes() copy of the pickle stream: loads accepts any buffer, and
    # the meta segment can reach inline_object_max_bytes (100KB) — on the
    # 1MB get path this plus the out-of-band views below keeps the read
    # fully zero-copy over the shm arena.
    pickled = data[off : off + plen]
    off = _aligned(off + plen)
    bufs = []
    for l in lens:
        bufs.append(data[off : off + l])
        off = _aligned(off + l)
    return pickle.loads(pickled, buffers=bufs)


def dumps(obj: Any) -> bytes:
    return serialize(obj).to_bytes()


def loads(data) -> Any:
    if isinstance(data, (bytes, bytearray)):
        data = memoryview(data)
    return deserialize(data)
