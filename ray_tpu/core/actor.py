"""Actors: class wrapper, handles, methods.

Reference analogues: ``ActorClass`` (`python/ray/actor.py:383`),
``ActorHandle`` (`:1024`), ``ActorMethod`` (`:98`).  An actor occupies a
dedicated worker process; method calls are dispatched FIFO by the raylet's
per-actor queue (`ray_tpu/core/raylet.py`), matching the reference's ordered
actor scheduling queues (`src/ray/core_worker/transport/actor_scheduling_queue.cc`).
"""

from __future__ import annotations

import copy
from typing import Dict, Optional

from ray_tpu.core.config import config
from ray_tpu.core.ids import ActorID, TaskID
from ray_tpu.core.remote_function import (
    _build_resources,
    _placement_from_opts,
    _prepare_env,
    deadline_from_opts,
)
from ray_tpu.core.task_spec import (
    ACTOR_CREATION_TASK,
    ACTOR_TASK,
    TaskSpec,
)
from ray_tpu.core.worker import global_worker
from ray_tpu.util.tracing import submit_with_span


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, **options):
        self._handle = handle
        self._method_name = method_name
        self._options = options

    def options(self, **new_options) -> "ActorMethod":
        merged = copy.copy(self._options)
        merged.update(new_options)
        return ActorMethod(self._handle, self._method_name, **merged)

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._method_name, args, kwargs,
                                    self._options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use '.{self._method_name}.remote()'."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = "Actor",
                 method_groups: Optional[Dict[str, str]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        # method -> concurrency group (actors with named groups only)
        self._method_groups = method_groups or {}

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def _invoke(self, method_name, args, kwargs, opts):
        worker = global_worker()
        out_args, out_kwargs, inner_refs = worker._prepare_args(args, kwargs)
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        if streaming:
            from ray_tpu.core.task_spec import STREAMING_RETURNS

            num_returns = STREAMING_RETURNS
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            kind=ACTOR_TASK,
            name=f"{self._class_name}.{method_name}",
            args=out_args,
            kwargs=out_kwargs,
            inner_refs=inner_refs or None,
            num_returns=num_returns,
            actor_id=self._actor_id,
            method_name=method_name,
            replicate=bool(opts.get("_replicate", False)),
            concurrency_group=(opts.get("concurrency_group")
                               or self._method_groups.get(method_name)),
            deadline=deadline_from_opts(opts),
        )
        refs = submit_with_span(worker, spec,
                                actor_id=self._actor_id.hex())
        if streaming:
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id)
        return refs[0] if spec.num_returns == 1 else refs

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        # Cache the bound ActorMethod on the instance: a submit burst
        # probes the same method once per call, and __getattr__ only
        # fires on lookup MISS — after this, attribute access is a plain
        # dict hit instead of a fresh allocation per call.  (.options()
        # still mints a new ActorMethod; the cached one is optionless.)
        method = ActorMethod(self, name)
        self.__dict__[name] = method
        return method

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_groups))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorClass:
    def __init__(self, cls, **options):
        self._cls = cls
        self._options = options
        self.__name__ = getattr(cls, "__name__", "Actor")

    def options(self, **new_options) -> "ActorClass":
        merged = copy.copy(self._options)
        merged.update(new_options)
        return ActorClass(self._cls, **merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, opts) -> ActorHandle:
        # Reference semantics: actors default to num_cpus=0 (they hold their
        # resources for life, so a 1-CPU default would starve the node).
        opts = dict(opts)
        opts.setdefault("num_cpus", 0)
        worker = global_worker()
        fid, blob = worker.register_function(self._cls)
        out_args, out_kwargs, inner_refs = worker._prepare_args(args, kwargs)
        actor_id = ActorID.from_random()
        max_restarts = opts.get("max_restarts",
                                config.actor_max_restarts_default)
        groups = opts.get("concurrency_groups")
        declared_conc = opts.get("max_concurrency", 1)
        method_groups: Optional[Dict[str, str]] = None
        if groups:
            if "_default" in groups:
                raise ValueError(
                    "'_default' is reserved; set its size via "
                    "max_concurrency")
            for gname, n in groups.items():
                if not isinstance(n, int) or n < 1:
                    raise ValueError(
                        f"concurrency group {gname!r} size must be a "
                        f"positive int, got {n!r}")
            # method -> group map from @ray_tpu.method tags, shipped on the
            # creation spec so the raylet can admit per group and any
            # handle (incl. get_actor) can stamp calls.
            method_groups = {}
            for mname, attr in vars(self._cls).items():
                tag = getattr(attr, "__ray_tpu_method_options__", None)
                if tag and tag.get("concurrency_group"):
                    g = tag["concurrency_group"]
                    if g not in groups:
                        raise ValueError(
                            f"method {mname!r} tagged with undeclared "
                            f"concurrency group {g!r}")
                    method_groups[mname] = g
            concurrency_groups = {"_default": declared_conc, **groups}
            # raylet total admission cap = sum of per-group slots
            total_concurrency = declared_conc + sum(groups.values())
        else:
            concurrency_groups = None
            total_concurrency = declared_conc
        # Checkpointable actors (reference: Ray actor checkpointing
        # lineage, SURVEY §5): opt-in protocol — the class defines
        # __ray_save__(self) -> state and __ray_restore__(self, state);
        # the worker snapshots every `checkpoint_interval` completed
        # calls and a restart restores from the latest snapshot instead
        # of starting cold.
        checkpoint_interval = int(opts.get("checkpoint_interval", 0) or 0)
        if checkpoint_interval < 0:
            raise ValueError("checkpoint_interval must be >= 0")
        if checkpoint_interval:
            for proto in ("__ray_save__", "__ray_restore__"):
                if not callable(getattr(self._cls, proto, None)):
                    raise TypeError(
                        f"checkpoint_interval requires the actor class to "
                        f"define {proto}")
            if groups or declared_conc > 1:
                # a snapshot taken while other threads mutate the instance
                # would tear state — checkpointing is sync-actor only
                raise ValueError(
                    "checkpoint_interval requires a plain sync actor "
                    "(max_concurrency=1, no concurrency groups)")
        placement = _placement_from_opts(opts) or {}
        if opts.get("name"):
            placement["name"] = opts["name"]
            placement["namespace"] = opts.get("namespace", "")
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            kind=ACTOR_CREATION_TASK,
            name=f"{self.__name__}.__init__",
            function_blob=blob,
            function_id=fid,
            args=out_args,
            kwargs=out_kwargs,
            inner_refs=inner_refs or None,
            num_returns=1,
            resources=_build_resources(opts),
            max_restarts=max_restarts,
            max_concurrency=total_concurrency,
            checkpoint_interval=checkpoint_interval,
            concurrency_groups=concurrency_groups,
            method_groups=method_groups,
            actor_id=actor_id,
            runtime_env=_prepare_env(worker, opts.get("runtime_env")),
            placement=placement or None,
        )
        worker.submit_spec(spec)
        return ActorHandle(actor_id, self.__name__, method_groups)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            f"use '{self.__name__}.remote()'."
        )


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    worker = global_worker()
    if worker.mode == "driver":
        raylet = worker.raylet
        # Through the event loop: an actor created just before via the
        # async submit path is guaranteed registered once this runs.
        info = raylet.call(
            lambda: raylet.gcs.lookup_named_actor(namespace, name)).result()
        if info is None:
            raise ValueError(f"no actor named {name!r}")
        if info.get("state") == "dead":
            from ray_tpu.core.exceptions import ActorDiedError

            raise ActorDiedError(
                info["actor_id"].hex(),
                info.get("death_reason", "actor is dead"))
        aid = ActorID(info["actor_id"])
        if info.get("spec_blob"):
            import cloudpickle as _cp

            creation_spec = _cp.loads(info["spec_blob"])
        else:
            raylet = worker.raylet
            creation_spec = raylet.call(
                lambda: raylet._actors[aid].creation_spec).result()
    else:
        info = worker._request("named_actor", name=name, namespace=namespace)
        aid, creation_spec = info["actor_id"], info["creation_spec"]
    return ActorHandle(aid, creation_spec.name.split(".")[0],
                       getattr(creation_spec, "method_groups", None))


def kill(actor: ActorHandle, no_restart: bool = True):
    worker = global_worker()
    if worker._direct is not None:
        # the kill travels the raylet path; frames already in flight on a
        # direct channel must reconcile rather than race the SIGKILL
        worker._direct.forget_actor(actor.actor_id)
    if worker.mode == "driver":
        worker.raylet.call_async(
            worker.raylet.kill_actor, actor.actor_id, no_restart
        )
    elif worker.mode == "local":
        worker._actors.pop(actor.actor_id, None)
    else:
        worker._request("kill_actor", actor_id=actor.actor_id,
                        no_restart=no_restart)
