"""Actors: class wrapper, handles, methods.

Reference analogues: ``ActorClass`` (`python/ray/actor.py:383`),
``ActorHandle`` (`:1024`), ``ActorMethod`` (`:98`).  An actor occupies a
dedicated worker process; method calls are dispatched FIFO by the raylet's
per-actor queue (`ray_tpu/core/raylet.py`), matching the reference's ordered
actor scheduling queues (`src/ray/core_worker/transport/actor_scheduling_queue.cc`).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional

from ray_tpu.core.config import config
from ray_tpu.core.ids import ActorID, TaskID
from ray_tpu.core.remote_function import (
    _build_resources,
    _placement_from_opts,
    _prepare_env,
)
from ray_tpu.core.task_spec import (
    ACTOR_CREATION_TASK,
    ACTOR_TASK,
    TaskSpec,
)
from ray_tpu.core.worker import global_worker


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, **options):
        self._handle = handle
        self._method_name = method_name
        self._options = options

    def options(self, **new_options) -> "ActorMethod":
        merged = copy.copy(self._options)
        merged.update(new_options)
        return ActorMethod(self._handle, self._method_name, **merged)

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._method_name, args, kwargs,
                                    self._options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use '.{self._method_name}.remote()'."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = "Actor"):
        self._actor_id = actor_id
        self._class_name = class_name

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def _invoke(self, method_name, args, kwargs, opts):
        worker = global_worker()
        out_args, out_kwargs = worker._prepare_args(args, kwargs)
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        if streaming:
            from ray_tpu.core.task_spec import STREAMING_RETURNS

            num_returns = STREAMING_RETURNS
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            kind=ACTOR_TASK,
            name=f"{self._class_name}.{method_name}",
            args=out_args,
            kwargs=out_kwargs,
            num_returns=num_returns,
            actor_id=self._actor_id,
            method_name=method_name,
        )
        from ray_tpu.util.tracing import submit_with_span

        refs = submit_with_span(worker, spec,
                                actor_id=self._actor_id.hex())
        if streaming:
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id)
        return refs[0] if spec.num_returns == 1 else refs

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorClass:
    def __init__(self, cls, **options):
        self._cls = cls
        self._options = options
        self.__name__ = getattr(cls, "__name__", "Actor")

    def options(self, **new_options) -> "ActorClass":
        merged = copy.copy(self._options)
        merged.update(new_options)
        return ActorClass(self._cls, **merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, opts) -> ActorHandle:
        # Reference semantics: actors default to num_cpus=0 (they hold their
        # resources for life, so a 1-CPU default would starve the node).
        opts = dict(opts)
        opts.setdefault("num_cpus", 0)
        worker = global_worker()
        fid, blob = worker.register_function(self._cls)
        out_args, out_kwargs = worker._prepare_args(args, kwargs)
        actor_id = ActorID.from_random()
        max_restarts = opts.get("max_restarts",
                                config.actor_max_restarts_default)
        placement = _placement_from_opts(opts) or {}
        if opts.get("name"):
            placement["name"] = opts["name"]
            placement["namespace"] = opts.get("namespace", "")
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            kind=ACTOR_CREATION_TASK,
            name=f"{self.__name__}.__init__",
            function_blob=blob,
            function_id=fid,
            args=out_args,
            kwargs=out_kwargs,
            num_returns=1,
            resources=_build_resources(opts),
            max_restarts=max_restarts,
            max_concurrency=opts.get("max_concurrency", 1),
            actor_id=actor_id,
            runtime_env=_prepare_env(worker, opts.get("runtime_env")),
            placement=placement or None,
        )
        worker.submit_spec(spec)
        return ActorHandle(actor_id, self.__name__)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            f"use '{self.__name__}.remote()'."
        )


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    worker = global_worker()
    if worker.mode == "driver":
        raylet = worker.raylet
        # Through the event loop: an actor created just before via the
        # async submit path is guaranteed registered once this runs.
        info = raylet.call(
            lambda: raylet.gcs.lookup_named_actor(namespace, name)).result()
        if info is None:
            raise ValueError(f"no actor named {name!r}")
        aid = ActorID(info["actor_id"])
        if info.get("spec_blob"):
            import cloudpickle as _cp

            creation_spec = _cp.loads(info["spec_blob"])
        else:
            raylet = worker.raylet
            creation_spec = raylet.call(
                lambda: raylet._actors[aid].creation_spec).result()
    else:
        info = worker._request("named_actor", name=name, namespace=namespace)
        aid, creation_spec = info["actor_id"], info["creation_spec"]
    return ActorHandle(aid, creation_spec.name.split(".")[0])


def kill(actor: ActorHandle, no_restart: bool = True):
    worker = global_worker()
    if worker.mode == "driver":
        worker.raylet.call_async(
            worker.raylet.kill_actor, actor.actor_id, no_restart
        )
    elif worker.mode == "local":
        worker._actors.pop(actor.actor_id, None)
    else:
        worker._request("kill_actor", actor_id=actor.actor_id,
                        no_restart=no_restart)
