"""Pull manager: policy layer of the zero-copy data plane.

Reference analogue: ``PullManager`` (`src/ray/object_manager/pull_manager.h:52`)
— admission control over total in-flight pull bytes, dedup of concurrent
requests for one object, chunk pipelining, and retry with source rotation.
On top of the reference semantics this one stripes chunk ranges across
MULTIPLE holders when the directory lists more than one (the reference
pulls a whole object from a single picked location), rebalancing work-stealing
style: every source that finishes a range grabs the next unassigned one, so
a stalled source simply stops winning ranges.

Threading: ``request``/``on_node_dead``/``tick`` run on the raylet event
thread; range completions arrive on DataChannel receiver threads.  One lock
guards all state; completions hop back to the event loop via ``post``
(raylet.call_async) so ``_object_in_store`` and friends stay event-thread
only.
"""

from __future__ import annotations

import heapq
import itertools
import queue as _queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.core.config import config
from ray_tpu.core.data_channel import DataChannel
from ray_tpu.core.ids import ObjectID
from ray_tpu.util.locks import make_lock
from ray_tpu.util.retry import BackoffPolicy

config.define("data_dial_attempts", int, 3,
              "Connect attempts per holder when dialing a data channel "
              "(unified jittered-exponential backoff between attempts) "
              "before the holder is tombstoned off the data plane.")

config.define("pull_max_inflight_bytes", int, 256 << 20,
              "Admission cap on total bytes of in-flight object pulls "
              "(reference: RAY_object_manager_max_bytes_in_flight).  Pulls "
              "beyond the cap wait in a FIFO+priority queue (task-argument "
              "pulls ahead of get()/wait() prefetch).")
config.define("pull_stripe_bytes", int, 8 << 20,
              "Range granularity for data-plane pulls: the unit of "
              "multi-source striping and of pipelining within one source.")
config.define("pull_pipeline_depth", int, 2,
              "Outstanding ranges per source per pull (keeps the pipe full "
              "while a range lands).")
config.define("pull_range_timeout_s", float, 20.0,
              "A range in flight longer than this rotates to another "
              "holder (source stall detection); with no alternative the "
              "channel is dropped and the pull retries via the directory.")
config.define("pull_max_sources", int, 4,
              "Max holders one pull stripes across.")


class _Pull:
    __slots__ = ("oid", "size", "priority", "channels", "dest", "buf",
                 "created", "unassigned", "inflight", "done_bytes",
                 "bytes_by_source", "meta_rid", "meta_tried", "meta_t",
                 "meta_chan", "state", "started", "charged")

    def __init__(self, oid: ObjectID, size: int, priority: int):
        self.oid = oid
        self.size = size
        self.priority = priority
        self.charged = 0  # bytes charged against the admission cap
        self.channels: List[DataChannel] = []
        self.dest: Optional[memoryview] = None   # store.create() buffer
        self.buf: Optional[bytearray] = None     # store-full fallback
        self.created = False
        self.unassigned: List[Tuple[int, int]] = []  # (offset, length) LIFO
        # rid -> (channel, offset, length, start_time)
        self.inflight: Dict[int, Tuple[DataChannel, int, int, float]] = {}
        self.done_bytes = 0
        self.bytes_by_source: Dict[str, int] = {}
        self.meta_rid: Optional[int] = None
        self.meta_tried = 0
        self.meta_t = 0.0  # last META request time (stall watchdog)
        self.meta_chan: Optional[DataChannel] = None
        self.state = "queued"  # queued | dialing | meta | active
        self.started = time.monotonic()


class PullManager:
    def __init__(
        self,
        node_id: str,
        store_fn: Callable[[], object],
        data_addr_fn: Callable[[str], Optional[Tuple[str, int]]],
        post: Callable[..., None],
        on_done: Callable[[ObjectID], None],
        on_fail: Callable[[ObjectID, List[str]], None],
        hello_fn: Optional[Callable[[], Tuple[str, int]]] = None,
    ):
        """``data_addr_fn``: peer node_id -> (host, data_port) or None —
        called on the event thread at request time only.  ``post`` hops a
        closure onto the raylet event loop; ``on_done``/``on_fail`` are
        delivered through it.  ``hello_fn`` returns this node's
        ``(node_id, incarnation)`` — the identity every dialed data
        channel presents for the holder's incarnation-fencing check."""
        self.node_id = node_id
        self._store_fn = store_fn
        self._data_addr_fn = data_addr_fn
        self._post = post
        self._on_done = on_done
        self._on_fail = on_fail
        self._hello_fn = hello_fn
        self._lock = make_lock("pull_manager.state")
        # SUSPECT holders (failure-detector state from node_suspect pubsub):
        # new pulls put them last in line and active pulls rotate striped
        # ranges away from them — routing-only, nothing is torn down, so a
        # false suspicion costs a rebalance, not a failed pull.
        self._suspect: set = set()                   # guard: _lock
        self._rid = itertools.count(1)
        self._seq = itertools.count()
        self._pulls: Dict[ObjectID, _Pull] = {}      # guard: _lock
        self._queue: list = []                       # guard: _lock
        self._queued: Dict[ObjectID, _Pull] = {}     # guard: _lock
        self._rid_to_pull: Dict[int, _Pull] = {}     # guard: _lock
        self._channels: Dict[str, DataChannel] = {}  # guard: _lock
        self._inflight_bytes = 0                     # guard: _lock
        self._closed = False
        # Nodes with no dialable data channel (dial failed / no data_port):
        # node_id -> tombstone expiry.  Lets request() refuse synchronously
        # so the caller falls back to the control-plane path instead of
        # re-dialing a dead host on every retry.  Event thread (request) +
        # dialer thread (_dial) both touch it; entries are independent and
        # dict get/set/del are GIL-atomic, so it rides without the lock.
        self._no_data_plane: Dict[str, float] = {}
        # Blocking TCP dials run on a dedicated dialer thread — NEVER on
        # the raylet event thread (a blackholed holder would stall
        # heartbeats for a connect timeout and get this node declared
        # dead).
        self._dial_q: _queue.SimpleQueue = _queue.SimpleQueue()
        self._dialer_started = False
        # ---- cumulative stats (read by metrics flush + tests) ----
        self._bytes_total = 0                        # guard: _lock
        self._chunks_total = 0                       # guard: _lock
        self._source_switches = 0                    # guard: _lock
        self._multi_source_pulls = 0                 # guard: _lock
        self._completed = 0                          # guard: _lock
        self._failed = 0                             # guard: _lock
        self._last_completed: Optional[dict] = None  # guard: _lock

    # ------------------------------------------------------------- public

    def active(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._pulls or oid in self._queued

    def _dialable(self, node: str) -> bool:
        exp = self._no_data_plane.get(node)
        if exp is None:
            return True
        if time.monotonic() > exp:
            del self._no_data_plane[node]
            return True
        return False

    def request(self, oid: ObjectID, size: int, locations: List[str],
                priority: int = 1) -> bool:
        """Start (or queue) a pull.  Returns False when NO holder is
        reachable on the data plane (per the tombstone cache) — the caller
        falls back to the control-plane pull path.  Runs on the raylet
        event thread; TCP dials for not-yet-connected holders happen on
        the dialer thread."""
        if self._closed:
            return False
        locs = [n for n in locations if self._dialable(n)]
        if not locs:
            return False
        # SUSPECT holders sort last: still usable (a suspicion is not a
        # death), but healthy holders win the stripe assignments.
        # unguarded-ok: set membership is GIL-atomic; staleness only
        # affects ordering.
        locs.sort(key=lambda n: n in self._suspect)
        cap_src = max(1, config.pull_max_sources)
        need_dial = False
        with self._lock:
            if oid in self._pulls or oid in self._queued:
                # dedup; an arg-priority request bumps a queued prefetch
                # (fresh heap entry — the stale one pops as a no-op)
                queued = self._queued.get(oid)
                if queued is not None and priority < queued.priority:
                    queued.priority = priority
                    if queued.state == "queued":
                        heapq.heappush(self._queue,
                                       (priority, next(self._seq), oid))
                return True
            pull = _Pull(oid, max(0, size), priority)
            live = [self._channels[n] for n in locs[:cap_src]
                    if n in self._channels and self._channels[n].alive]
            if len(live) == len(locs[:cap_src]):
                # every holder already connected: straight to admission
                pull.channels = live
                self._queued[oid] = pull
                heapq.heappush(self._queue,
                               (priority, next(self._seq), oid))
                actions = self._admit_locked()
            else:
                # at least one holder needs a (blocking) dial: hand off
                pull.state = "dialing"
                self._queued[oid] = pull
                actions = []
                need_dial = True
        if actions:
            self._run_actions(actions)
        if need_dial:
            self._dial_q.put((oid, locs[:cap_src]))
            if not self._dialer_started:
                self._dialer_started = True
                threading.Thread(target=self._dialer_loop,
                                 name="pull-dialer", daemon=True).start()
        return True

    def _dialer_loop(self):
        while not self._closed:
            try:
                oid, locs = self._dial_q.get(timeout=5.0)
            except _queue.Empty:
                continue
            channels = self._dial(locs)
            with self._lock:
                pull = self._queued.get(oid)
                if pull is None or pull.state != "dialing":
                    continue
                pull.channels = [c for c in channels if c.alive]
                if not pull.channels:
                    del self._queued[oid]
                    self._failed += 1
                    fail = True
                    actions = []
                else:
                    fail = False
                    pull.state = "queued"
                    heapq.heappush(self._queue,
                                   (pull.priority, next(self._seq), oid))
                    actions = self._admit_locked()
            if fail:
                # tombstones are recorded by _dial; the raylet's retry will
                # see request() return False and use the fallback path
                self._post(self._on_fail, oid, [])
            else:
                self._run_actions(actions)

    def on_node_dead(self, node_id: str):
        with self._lock:
            chan = self._channels.get(node_id)
            self._suspect.discard(node_id)
        if chan is not None:
            chan.close()  # receiver thread delivers the "closed" event

    def _rotate_range_locked(self, pull, rid, chan, off, ln, others,
                             now, actions):  # requires: _lock
        """Reassign one in-flight range DIRECTLY to the least-loaded other
        holder (the generic assigner could hand the range straight back to
        the vacated slot) — temporarily exceeding its pipeline depth beats
        staying on a stalled/suspect source."""
        chan.cancel(rid)
        del pull.inflight[rid]
        self._rid_to_pull.pop(rid, None)
        self._source_switches += 1
        other = min(
            others,
            key=lambda c: sum(1 for e in pull.inflight.values()
                              if e[0] is c))
        new_rid = next(self._rid)
        pull.inflight[new_rid] = (other, off, ln, now)
        self._rid_to_pull[new_rid] = pull
        sink = (pull.dest[off:off + ln]
                if pull.dest is not None else None)
        actions.append(("range", other, new_rid, pull.oid, off, ln, sink))

    def on_node_suspect(self, node_id: str, suspect: bool):
        """Failure-detector routing signal (raylet event thread): a
        SUSPECT holder's in-flight striped ranges rotate to the pull's
        other live sources immediately instead of waiting out the stall
        watchdog; the channel stays open and nothing fails — if the node
        recovers, it simply starts winning ranges again."""
        actions = []
        now = time.monotonic()
        with self._lock:
            if not suspect:
                self._suspect.discard(node_id)
                return
            self._suspect.add(node_id)
            for pull in self._pulls.values():
                others = [c for c in pull.channels
                          if c.node_id != node_id and c.alive]
                if not others:
                    continue  # sole source: keep it, slow beats dead
                for rid, (chan, off, ln, _t0) in list(pull.inflight.items()):
                    if chan.node_id != node_id:
                        continue
                    self._rotate_range_locked(pull, rid, chan, off, ln,
                                              others, now, actions)
        self._run_actions(actions)

    def tick(self):
        """Watchdog (event-thread timer): rotate stalled ranges to another
        holder; with no alternative, drop the channel so the pull fails
        fast and retries through the directory."""
        timeout = config.pull_range_timeout_s
        if timeout <= 0:
            return
        now = time.monotonic()
        stalled_channels = []
        with self._lock:
            actions = []
            for pull in list(self._pulls.values()):
                # META stall: the reply rides the holder's (sequentially
                # served) connection, so a wedged serve thread starves it
                # forever without this — close the serving channel and let
                # the closed event rotate or fail the pull.
                if (pull.state == "meta"
                        and now - pull.meta_t >= timeout
                        and pull.meta_chan is not None):
                    stalled_channels.append(pull.meta_chan)
                    pull.meta_t = now  # don't re-close every tick
                    continue
                for rid, (chan, off, ln, t0) in list(pull.inflight.items()):
                    if now - t0 < timeout:
                        continue
                    others = [c for c in pull.channels
                              if c is not chan and c.alive]
                    if others:
                        self._rotate_range_locked(pull, rid, chan, off, ln,
                                                  others, now, actions)
                    else:
                        stalled_channels.append(chan)
        self._run_actions(actions)
        for chan in stalled_channels:
            chan.close()

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight_bytes": self._inflight_bytes,
                "queued": len(self._queue),
                "active": len(self._pulls),
                "bytes_total": self._bytes_total,
                "chunks_total": self._chunks_total,
                "source_switches": self._source_switches,
                "multi_source_pulls": self._multi_source_pulls,
                "completed": self._completed,
                "failed": self._failed,
                "last_completed": dict(self._last_completed)
                if self._last_completed else None,
            }

    def close(self):
        self._closed = True
        with self._lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for chan in channels:
            chan.close()

    # ------------------------------------------------------- channel plumbing

    def _dial(self, locations: List[str]) -> List[DataChannel]:
        """Connect (or reuse) data channels for up to pull_max_sources
        holders.  Runs on the DIALER thread (blocking connects must stay
        off the raylet event loop).  Each holder gets
        ``data_dial_attempts`` connects under the unified backoff policy
        (a restarting peer often accepts on the second try); nodes that
        still can't be dialed — no data_port registered, or every connect
        failed — get a tombstone so callers stop retrying the data plane
        against them for a while."""
        out = []
        policy = BackoffPolicy()
        for node in locations[:max(1, config.pull_max_sources)]:
            with self._lock:
                chan = self._channels.get(node)
            if chan is not None and chan.alive:
                out.append(chan)
                continue
            addr = self._data_addr_fn(node)
            if addr is None:
                self._no_data_plane[node] = time.monotonic() + 30.0
                continue
            chan = None
            identity = self._hello_fn() if self._hello_fn is not None \
                else None
            for attempt in range(max(1, config.data_dial_attempts)):
                if self._closed:
                    return out
                try:
                    chan = DataChannel(node, addr, self._on_event,
                                       identity=identity)
                    break
                except (ConnectionRefusedError, TimeoutError):
                    # Refused: the peer process is gone.  Timeout: the
                    # HOST is gone (preemption — the dominant failure on
                    # the target fleet) and already cost connect_timeout.
                    # Retrying either is futile, and every sleep here
                    # serializes in front of all other queued dials on
                    # this (single) dialer thread.
                    break
                except OSError:
                    # reset/unreachable (fast failures): plausibly a
                    # restarting peer — retry under the unified backoff
                    if attempt + 1 < max(1, config.data_dial_attempts):
                        time.sleep(policy.delay(attempt))
            if chan is None:
                self._no_data_plane[node] = time.monotonic() + 30.0
                continue
            # Install under the lock, and never clobber a channel some
            # other path installed while this dial was in flight — the
            # overwritten entry would leak an open connection (its
            # receiver thread would also keep feeding stale events).
            with self._lock:
                existing = self._channels.get(node)
                if existing is not None and existing.alive \
                        and existing is not chan:
                    loser = chan
                    chan = existing
                else:
                    self._channels[node] = chan
                    loser = None
            if loser is not None:
                loser.close()
            out.append(chan)
        return out

    # ---------------------------------------------------------- admission

    def _admit_locked(self) -> list:  # requires: _lock
        """Admit queued pulls while under the in-flight byte cap (always at
        least one when nothing is active, so an object bigger than the cap
        still moves).  Returns channel actions to run outside the lock."""
        cap = max(1, config.pull_max_inflight_bytes)
        actions = []
        while self._queue:
            _, _, oid = self._queue[0]
            pull = self._queued.get(oid)
            if pull is None or pull.state == "dialing":  # stale / not ready
                heapq.heappop(self._queue)
                continue
            # Unknown size (META pending) is charged a provisional stripe's
            # worth so a burst of size-0 directory entries can't blow
            # through the cap; the true size adjusts the charge on META.
            est = pull.size or max(1, config.pull_stripe_bytes)
            if self._pulls and self._inflight_bytes + est > cap:
                break
            heapq.heappop(self._queue)
            del self._queued[oid]
            pull.charged = est
            self._inflight_bytes += est
            actions.extend(self._start_locked(pull))
        return actions

    def _start_locked(self, pull: _Pull) -> list:  # requires: _lock
        pull.channels = [c for c in pull.channels if c.alive]
        if not pull.channels:
            return [("fail", pull, [])]
        self._pulls[pull.oid] = pull
        if pull.size <= 0:
            # size unknown: ask the first holder (META) before allocating
            pull.state = "meta"
            rid = next(self._rid)
            pull.meta_rid = rid
            pull.meta_t = time.monotonic()
            pull.meta_chan = pull.channels[pull.meta_tried
                                           % len(pull.channels)]
            self._rid_to_pull[rid] = pull
            return [("meta", pull.meta_chan, rid, pull.oid)]
        return self._activate_locked(pull)

    def _activate_locked(self, pull: _Pull) -> list:  # requires: _lock
        """Size known: allocate the destination and fan the first ranges
        out round-robin across every live holder."""
        pull.state = "active"
        self._inflight_bytes += pull.size - pull.charged
        pull.charged = pull.size
        store = self._store_fn()
        if store is None:
            return [("fail", pull, [])]
        try:
            pull.dest = store.create(pull.oid, pull.size,
                                     allow_evict=not config.object_store_spill)
            pull.created = True
        except FileExistsError:
            # raced another path; the object is (or is becoming) local
            return [("done", pull)]
        except Exception:  # noqa: BLE001 — store full
            if not config.object_store_spill:
                return [("fail", pull, [])]
            pull.buf = bytearray(pull.size)
            pull.dest = memoryview(pull.buf)
        if pull.size == 0:
            return [("done", pull)]
        stripe = max(64 << 10, config.pull_stripe_bytes)
        # LIFO assignment order doesn't matter for correctness; build the
        # range list back-to-front so .pop() hands out ascending offsets.
        pull.unassigned = [
            (off, min(stripe, pull.size - off))
            for off in range(0, pull.size, stripe)
        ][::-1]
        return self._assign_locked(pull)

    def _assign_locked(self, pull: _Pull) -> list:  # requires: _lock
        """Top up every live source to pipeline_depth outstanding ranges."""
        actions = []
        depth = max(1, config.pull_pipeline_depth)
        live = [c for c in pull.channels if c.alive]
        # SUSPECT holders stop winning new ranges while any healthy source
        # remains (failure-detector routing; a lone suspect still serves).
        healthy = [c for c in live if c.node_id not in self._suspect]
        if healthy:
            live = healthy
        if not live:
            if pull.inflight or not pull.unassigned:
                return actions
            return [("fail", pull, [])]
        counts = {id(c): 0 for c in live}
        for chan, _off, _ln, _t in pull.inflight.values():
            if id(chan) in counts:
                counts[id(chan)] += 1
        for chan in itertools.cycle(live):
            if not pull.unassigned:
                break
            if all(counts[id(c)] >= depth for c in live):
                break
            if counts[id(chan)] >= depth:
                continue
            off, ln = pull.unassigned.pop()
            rid = next(self._rid)
            pull.inflight[rid] = (chan, off, ln, time.monotonic())
            self._rid_to_pull[rid] = pull
            counts[id(chan)] += 1
            sink = pull.dest[off:off + ln] if pull.dest is not None else None
            actions.append(("range", chan, rid, pull.oid, off, ln, sink))
        return actions

    def _run_actions(self, actions: list):
        """Execute channel sends / completions collected under the lock."""
        for act in actions:
            kind = act[0]
            if kind == "range":
                _, chan, rid, oid, off, ln, sink = act
                if not chan.request_range(rid, oid, off, ln, sink):
                    # send failed -> channel closed itself; the "closed"
                    # event reassigns this rid
                    pass
            elif kind == "meta":
                _, chan, rid, oid = act
                chan.request_meta(rid, oid)
            elif kind == "done":
                self._finalize(act[1])
            elif kind == "fail":
                self._fail(act[1], act[2])

    # --------------------------------------------------------- channel events

    def _on_event(self, chan: DataChannel, rid: Optional[int], kind: str,
                  arg):
        """Receiver-thread callback from a DataChannel."""
        if kind == "closed":
            self._on_channel_closed(chan)
            return
        with self._lock:
            pull = self._rid_to_pull.pop(rid, None) if rid else None
            if pull is None:
                return
            if kind == "data":
                entry = pull.inflight.pop(rid, None)
                if entry is None:
                    return
                _, off, ln, _t = entry
                pull.done_bytes += ln
                pull.bytes_by_source[chan.node_id] = \
                    pull.bytes_by_source.get(chan.node_id, 0) + ln
                self._bytes_total += ln
                self._chunks_total += 1
                if pull.done_bytes >= pull.size and not pull.unassigned \
                        and not pull.inflight:
                    actions = [("done", pull)]
                else:
                    actions = self._assign_locked(pull)
            elif kind == "meta":
                if pull.state != "meta":
                    return
                pull.size = int(arg)
                pull.meta_rid = None
                actions = self._activate_locked(pull)
            else:  # "err" — this holder can't serve (freed / never had it)
                actions = self._drop_source_locked(pull, chan, rid)
        self._run_actions(actions)

    def _drop_source_locked(self, pull: _Pull, chan: DataChannel,  # requires: _lock
                            rid: Optional[int]) -> list:
        if pull.state == "meta":
            pull.meta_tried += 1
            others = [c for c in pull.channels if c is not chan and c.alive]
            if not others:
                return [("fail", pull, [chan.node_id])]
            pull.channels = others
            new_rid = next(self._rid)
            pull.meta_rid = new_rid
            pull.meta_t = time.monotonic()
            pull.meta_chan = others[pull.meta_tried % len(others)]
            self._rid_to_pull[new_rid] = pull
            return [("meta", pull.meta_chan, new_rid, pull.oid)]
        if rid is not None:
            entry = pull.inflight.pop(rid, None)
            if entry is not None:
                pull.unassigned.append((entry[1], entry[2]))
        before = len(pull.channels)
        pull.channels = [c for c in pull.channels
                         if c is not chan and c.alive]
        if not pull.channels:
            return [("fail", pull, [chan.node_id])]
        if len(pull.channels) < before:
            self._source_switches += 1
        return self._assign_locked(pull)

    def _on_channel_closed(self, chan: DataChannel):
        with self._lock:
            if self._channels.get(chan.node_id) is chan:
                del self._channels[chan.node_id]
            actions = []
            for pull in list(self._pulls.values()):
                if chan not in pull.channels and not any(
                        c is chan for c, *_ in pull.inflight.values()):
                    continue
                moved = False
                for rid, entry in list(pull.inflight.items()):
                    if entry[0] is chan:
                        del pull.inflight[rid]
                        self._rid_to_pull.pop(rid, None)
                        pull.unassigned.append((entry[1], entry[2]))
                        moved = True
                had = chan in pull.channels
                pull.channels = [c for c in pull.channels if c is not chan]
                # NB: channel death is NOT authoritative "object gone" —
                # fail with no bad_nodes so the retry keeps the directory
                # entry (an explicit "not here" ERR is what scrubs it).
                if pull.state == "meta" and had and not pull.channels:
                    actions.append(("fail", pull, []))
                    continue
                if pull.state == "meta" and had:
                    # retry meta on a surviving holder
                    new_rid = next(self._rid)
                    if pull.meta_rid is not None:
                        self._rid_to_pull.pop(pull.meta_rid, None)
                    pull.meta_rid = new_rid
                    pull.meta_t = time.monotonic()
                    pull.meta_chan = pull.channels[0]
                    self._rid_to_pull[new_rid] = pull
                    actions.append(("meta", pull.meta_chan, new_rid,
                                    pull.oid))
                    continue
                if not pull.channels:
                    actions.append(("fail", pull, []))
                    continue
                if moved or had:
                    self._source_switches += 1
                    actions.extend(self._assign_locked(pull))
            # a dead channel may also unblock queued admissions ( pulls that
            # failed shrink inflight bytes inside _fail, not here )
        self._run_actions(actions)

    # ------------------------------------------------------------ completion

    def _teardown_locked(self, pull: _Pull):  # requires: _lock
        self._pulls.pop(pull.oid, None)
        for rid in list(pull.inflight):
            chan = pull.inflight[rid][0]
            chan.cancel(rid)
            self._rid_to_pull.pop(rid, None)
        pull.inflight.clear()
        if pull.meta_rid is not None:
            self._rid_to_pull.pop(pull.meta_rid, None)
        self._inflight_bytes -= pull.charged
        pull.charged = 0
        if self._inflight_bytes < 0:
            self._inflight_bytes = 0

    def _finalize(self, pull: _Pull):
        """All bytes landed (receiver thread or event thread): seal (or
        spill) and hand completion to the event loop."""
        store = self._store_fn()
        with self._lock:
            self._teardown_locked(pull)
            self._completed += 1
            if len([s for s, b in pull.bytes_by_source.items() if b > 0]) >= 2:
                self._multi_source_pulls += 1
            self._last_completed = {
                "oid": pull.oid.hex(),
                "size": pull.size,
                "sources": dict(pull.bytes_by_source),
                "elapsed_s": time.monotonic() - pull.started,
            }
            actions = self._admit_locked()
        try:
            if pull.created:
                pull.dest = None
                store.seal(pull.oid)
                store.release(pull.oid)
            elif pull.buf is not None:
                pull.dest = None
                try:
                    dest = store.create(
                        pull.oid, pull.size,
                        allow_evict=not config.object_store_spill)
                    dest[:] = pull.buf
                    del dest
                    store.seal(pull.oid)
                    store.release(pull.oid)
                except FileExistsError:
                    pass
                except Exception:  # noqa: BLE001 — still full: spill
                    store.spill_raw(pull.oid, pull.buf)
                pull.buf = None
        except Exception:  # noqa: BLE001
            self._post(self._on_fail, pull.oid, [])
            self._run_actions(actions)
            return
        self._post(self._on_done, pull.oid)
        self._run_actions(actions)

    def _fail(self, pull: _Pull, bad_nodes: List[str]):
        store = self._store_fn()
        with self._lock:
            self._queued.pop(pull.oid, None)
            # Channels that may STILL be landing bytes into pull.dest (a
            # receiver that already popped its sink is mid recv_into and
            # chan.cancel() can't stop it) must be quiesced before the
            # allocation is freed, or the late bytes would write into a
            # reused arena region — silent corruption of another object.
            live = {e[0] for e in pull.inflight.values() if e[0].alive}
            self._teardown_locked(pull)
            self._failed += 1
            actions = self._admit_locked()
        if pull.created:
            for chan in live:
                chan.close()
                chan.join_receiver()
            pull.dest = None
            try:
                store.abort(pull.oid)
            except Exception:  # noqa: BLE001
                pass
        pull.buf = None
        self._post(self._on_fail, pull.oid, list(bad_nodes))
        self._run_actions(actions)
