"""Worker subprocess entrypoint — the ``default_worker.py`` equivalent.

Reference analogue: `python/ray/_private/workers/default_worker.py` +
``CoreWorker.run_task_loop`` (`python/ray/_raylet.pyx:2702`).

Threading model: a reader thread drains the raylet socket (demuxing task
dispatches from request replies) so that a task blocked in ``get()`` can
still receive its reply; the main thread is the single task executor.
"""

from __future__ import annotations

import argparse
import os
import queue
import socket
import sys
import threading
import traceback
from typing import Dict, Optional

import cloudpickle

from ray_tpu.core import protocol, serialization
from ray_tpu.core.config import config
from ray_tpu.core.exceptions import TaskError
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.task_spec import (
    ACTOR_CREATION_TASK,
    ACTOR_TASK,
    TaskSpec,
)
from ray_tpu.core.worker import WORKER, Worker, init_worker


class RemoteWorker(Worker):
    """Worker-process side of the control socket."""

    def __init__(self, sock: socket.socket):
        super().__init__(WORKER)
        self.sock = sock
        self.send_lock = threading.Lock()
        self.task_queue: "queue.Queue" = queue.Queue()
        self._rid = 0
        self._rid_lock = threading.Lock()
        self._pending: Dict[int, dict] = {}
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self):
        while True:
            try:
                msg = protocol.recv_msg(self.sock)
            except OSError:
                msg = None
            if msg is None:
                os._exit(0)  # raylet gone — die quietly
            t = msg.get("t")
            if t == "task":
                self.task_queue.put(msg)
            elif t == "reply":
                entry = self._pending.pop(msg["rid"], None)
                if entry is not None:
                    entry["msg"] = msg
                    entry["event"].set()
            elif t == "shutdown":
                os._exit(0)

    def _send(self, msg):
        protocol.send_msg(self.sock, msg, self.send_lock)

    def _request(self, op, **fields):
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        entry = {"event": threading.Event(), "msg": None}
        self._pending[rid] = entry
        self._send({"t": "request", "rid": rid, "op": op, **fields})
        entry["event"].wait()
        msg = entry["msg"]
        if not msg["ok"]:
            raise msg["error"]
        return msg["value"]


def _resolve_callable(worker: RemoteWorker, spec: TaskSpec, fn_blob):
    key = spec.function_id.binary() if spec.function_id else None
    if key is not None and key in worker._fn_cache:
        return worker._fn_cache[key]
    blob = fn_blob or spec.function_blob
    if blob is None and spec.function_id is not None:
        blob = worker._request("get_function", id=spec.function_id.binary())
    if blob is None:
        raise RuntimeError(f"no function payload for task {spec.name}")
    fn = cloudpickle.loads(blob)
    if key is not None:
        worker._fn_cache[key] = fn
    return fn


def _resolve_args(worker: RemoteWorker, spec: TaskSpec, arg_values):
    def resolve(entry):
        kind, payload = entry
        if kind == "v":
            return serialization.loads(payload)
        oid: ObjectID = payload
        blob = arg_values.get(oid.hex())
        if blob is not None:
            return serialization.loads(blob)
        if worker.store is None:
            raise RuntimeError("no object store attached")
        return worker.store.get(oid, timeout=60.0)

    args = [resolve(a) for a in spec.args]
    kwargs = {k: resolve(v) for k, v in spec.kwargs}
    return args, kwargs


def _package_results(worker: RemoteWorker, spec: TaskSpec, result):
    inline: Dict[str, bytes] = {}
    stored = []
    if spec.num_returns == 1:
        values = [result]
    else:
        values = list(result)
        if len(values) != spec.num_returns:
            raise ValueError(
                f"task {spec.name} declared num_returns={spec.num_returns} "
                f"but returned {len(values)} values"
            )
    for oid, val in zip(spec.return_ids(), values):
        ser = serialization.serialize(val)
        if ser.total_bytes() <= config.inline_object_max_bytes or worker.store is None:
            inline[oid.hex()] = ser.to_bytes()
        else:
            worker.store.put_serialized(oid, ser)
            stored.append(oid.hex())
    return inline, stored


def _apply_runtime_env(spec: TaskSpec):
    env = spec.runtime_env or {}
    wd = env.get("working_dir")
    if wd:
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)


def execute_task(worker: RemoteWorker, msg: dict):
    spec: TaskSpec = msg["spec"]
    try:
        _apply_runtime_env(spec)
        args, kwargs = _resolve_args(worker, spec, msg.get("arg_values", {}))
        if spec.kind == ACTOR_CREATION_TASK:
            cls = _resolve_callable(worker, spec, msg.get("fn_blob"))
            worker.actor_instance = cls(*args, **kwargs)
            worker.current_actor_id = spec.actor_id
            result = None
        elif spec.kind == ACTOR_TASK:
            if spec.method_name == "__ray_terminate__":
                worker._send({"t": "done", "task_id": spec.task_id, "ok": True,
                              "inline": {spec.return_ids()[0].hex():
                                         serialization.dumps(None)},
                              "stored": []})
                os._exit(0)
            inst = worker.actor_instance
            if inst is None:
                raise RuntimeError("actor instance missing")
            result = getattr(inst, spec.method_name)(*args, **kwargs)
        else:
            fn = _resolve_callable(worker, spec, msg.get("fn_blob"))
            result = fn(*args, **kwargs)
        inline, stored = _package_results(worker, spec, result)
        worker._send({"t": "done", "task_id": spec.task_id, "ok": True,
                      "inline": inline, "stored": stored})
    except Exception as e:  # noqa: BLE001
        tb = traceback.format_exc()
        err = TaskError(spec.name, tb, None)
        worker._send({
            "t": "done", "task_id": spec.task_id, "ok": False,
            "error": err, "retryable": spec.retry_exceptions,
        })


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--socket", required=True)
    parser.add_argument("--store", default=None)
    args = parser.parse_args()

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(args.socket)
    worker = RemoteWorker(sock)
    if args.store:
        worker.store = ShmObjectStore(args.store)
    init_worker(worker)
    worker._send({
        "t": "register",
        "pid": os.getpid(),
        "worker_id": worker.worker_id,
        "profile": os.environ.get("RAY_TPU_WORKER_PROFILE", "cpu"),
    })
    while True:
        msg = worker.task_queue.get()
        execute_task(worker, msg)


if __name__ == "__main__":
    main()
