"""Worker subprocess entrypoint — the ``default_worker.py`` equivalent.

Reference analogue: `python/ray/_private/workers/default_worker.py` +
``CoreWorker.run_task_loop`` (`python/ray/_raylet.pyx:2702`).

Threading model: a reader thread drains the raylet socket (demuxing task
dispatches from request replies) so that a task blocked in ``get()`` can
still receive its reply; the main thread is the single task executor.
"""

from __future__ import annotations

import argparse
import asyncio
import inspect
import os
import queue
import socket
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

import cloudpickle

from ray_tpu.core import protocol, serialization
from ray_tpu.core.config import config
from ray_tpu.core.exceptions import (
    BackPressureError,
    DeadlineExceededError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.task_spec import (
    ACTOR_CREATION_TASK,
    ACTOR_TASK,
    STREAMING_RETURNS,
    TaskSpec,
)
from ray_tpu.core.worker import WORKER, Worker, init_worker
from ray_tpu.util.locks import make_lock

#: control-flow errors that must reach the caller TYPED (not wrapped in
#: TaskError) and are never retried — backpressure rejections, deadline
#: expiry, cancellation
CONTROL_ERRORS = (BackPressureError, DeadlineExceededError,
                  TaskCancelledError)

#: Hot-path module refs, resolved once on first execution.  The execute
#: path used to run half a dozen ``from x import y`` statements PER CALL
#: (~15µs of sys.modules lookups); those imports are deferred only to
#: break import cycles at module-load time, so a lazy singleton pays the
#: deferral exactly once.
_HOT = None


def _hot():
    global _HOT
    if _HOT is None:
        from ray_tpu.core import runtime_env
        from ray_tpu.core.worker import global_worker
        from ray_tpu.runtime_context import (
            _current_deadline,
            _current_task_id,
        )
        from ray_tpu.util import chaos, profiling, tracing

        _HOT = (_current_deadline, _current_task_id, chaos, profiling,
                tracing, runtime_env, global_worker)
    return _HOT


def _async_raise(thread_ident: int, exc_type) -> bool:
    """Raise ``exc_type`` asynchronously in another thread (delivered at
    its next bytecode boundary) — the CPython seam behind mid-exec
    cancellation/deadlines, same mechanism the reference uses for
    non-force task cancellation (KeyboardInterrupt into the executor)."""
    import ctypes

    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_ident), ctypes.py_object(exc_type))
    if res > 1:  # pragma: no cover — defensive: undo a multi-target hit
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_ident), None)
        return False
    return res == 1


class _CancelRegistry:
    """Per-process cancellation + deadline enforcement for executing
    tasks.  One watchdog thread (lazy) arms every deadline; cancel frames
    (raylet ``cancel`` / direct ``dcancel``) interrupt the registered
    executor thread or mark a not-yet-started task for the pre-exec
    check.  Sync executions register their thread ident; asyncio actor
    calls register with ident None (cooperative pre-exec check only — an
    async exception into the shared loop thread would kill the loop)."""

    def __init__(self):
        self._lock = make_lock("worker.cancel_registry")
        self._cancelled: dict = {}  # task_id -> exc type  # guard: _lock
        self._running: dict = {}  # task_id -> thread ident or None  # guard: _lock
        self._interrupted: set = set()  # tids already async-raised  # guard: _lock
        self._deadlines: list = []  # heap[(deadline, task_id)]  # guard: _lock
        self._wake = threading.Condition(self._lock)
        self._watchdog_started = False  # guard: _lock

    # ---- cancel frames (reader / direct-conn threads) ----

    def cancel(self, task_id, exc_type=TaskCancelledError):
        """Mark cancelled; interrupt now if the task is mid-exec.  The
        async raise happens UNDER the lock (deregister serializes behind
        it, so the exception can never land on a thread that already
        moved on to the next task) and at most ONCE per task id — the
        same cancel arriving on two paths (dcancel + raylet frame, or
        cancel racing the deadline watchdog) must not deliver a second
        exception into the except-handler that is reporting the first
        (the aborted done frame would hang the caller forever)."""
        with self._lock:
            self._cancelled[task_id] = exc_type
            while len(self._cancelled) > 4096:  # bounded: stale ids age out
                self._cancelled.pop(next(iter(self._cancelled)))
            entry = self._running.get(task_id)
            if entry is not None and task_id not in self._interrupted:
                self._interrupted.add(task_id)
                self._interrupt(entry, exc_type)

    @staticmethod
    def _interrupt(entry, exc_type):
        """Deliver the interrupt for a registry entry: thread ident ->
        async exception at the next bytecode; asyncio record ->
        task.cancel() scheduled on the loop (raises CancelledError at
        the coroutine's next await — an async exception into the shared
        loop thread would kill every interleaved call)."""
        if isinstance(entry, tuple):
            loop, atask = entry[1], entry[2]
            loop.call_soon_threadsafe(atask.cancel)
        else:
            _async_raise(entry, exc_type)

    def check(self, task_id):
        """Pre-exec seam: raise if this task was cancelled before it ran."""
        if not self._cancelled:  # unguarded-ok: GIL-atomic emptiness peek; a cancel landing this instant is the same race as it landing one call later
            return
        with self._lock:
            exc = self._cancelled.get(task_id)
        if exc is not None:
            raise exc()

    def cancelled_as(self, task_id):
        """The typed error this task was cancelled with (None if it
        wasn't) — lets the asyncio path convert a CancelledError back
        into the control error the caller dispatches on."""
        with self._lock:
            return self._cancelled.get(task_id)

    # ---- execution registration ----

    def register(self, task_id, ident, deadline):
        with self._lock:
            exc = self._cancelled.get(task_id)
            if exc is not None:
                # cancel frame landed between the pre-exec check and
                # registration: raise HERE (we are on the executor
                # thread) instead of executing uninterruptible
                raise exc()
            self._running[task_id] = ident
            if deadline is not None and ident is not None \
                    and config.deadlines:
                self._arm_deadline(task_id, deadline)

    def register_async(self, task_id, loop, atask, deadline):
        """Asyncio actor call: interruptible via task.cancel() on the
        loop (CancelledError at the next await).  Raises like register()
        when a cancel already landed."""
        with self._lock:
            exc = self._cancelled.get(task_id)
            if exc is not None:
                raise exc()
            self._running[task_id] = ("async", loop, atask)
            if deadline is not None and config.deadlines:
                self._arm_deadline(task_id, deadline)

    def _arm_deadline(self, task_id, deadline):  # requires: _lock
        import heapq

        heapq.heappush(self._deadlines, (deadline, task_id))
        if not self._watchdog_started:
            self._watchdog_started = True
            threading.Thread(target=self._watchdog_loop,
                             name="deadline-watchdog",
                             daemon=True).start()
        self._wake.notify()

    def deregister(self, task_id):
        with self._lock:
            self._running.pop(task_id, None)
            self._cancelled.pop(task_id, None)
            self._interrupted.discard(task_id)

    def _watchdog_loop(self):
        import heapq

        while True:
            with self._lock:
                now = time.time()
                while self._deadlines and self._deadlines[0][0] <= now:
                    _, task_id = heapq.heappop(self._deadlines)
                    entry = self._running.get(task_id)
                    if entry is not None \
                            and task_id not in self._interrupted:
                        self._cancelled[task_id] = DeadlineExceededError
                        self._interrupted.add(task_id)
                        self._interrupt(entry, DeadlineExceededError)
                timeout = (self._deadlines[0][0] - now
                           if self._deadlines else None)
                self._wake.wait(timeout)


class RemoteWorker(Worker):
    """Worker-process side of the control socket."""

    def __init__(self, sock: socket.socket):
        super().__init__(WORKER)
        self.sock = sock
        self.send_lock = make_lock("remote_worker.send")
        self.task_queue: "queue.Queue" = queue.Queue()
        # Actor concurrency (reference: threaded concurrency groups + asyncio
        # actors, `src/ray/core_worker/transport/concurrency_group_manager.cc`)
        self.actor_executor: Optional[ThreadPoolExecutor] = None
        self.group_executors: Optional[Dict[str, ThreadPoolExecutor]] = None
        self.actor_loop: Optional[asyncio.AbstractEventLoop] = None
        # Checkpointable actors: snapshot __ray_save__() every
        # checkpoint_interval completed calls (sync actors only — set by
        # the creation task).  All three fields touched only on the main
        # executor thread.
        self.checkpoint_interval = 0
        self.checkpoint_calls = 0  # completed calls since last snapshot
        self.checkpoint_seq = 0
        # Direct transport: callee-side listener (started in main once the
        # store is attached) and the restart generation the hosting raylet
        # stamped into the creation spec — direct hellos must match it.
        self.direct_server = None
        self.actor_generation = 0
        # lease token the raylet granted on this worker (direct_lease
        # control message); lease hellos must present exactly this id
        self.active_lease_id = None
        # Serializes task execution between the main loop and a direct
        # conn thread executing inline (plain sync actors / leased pool
        # workers) — single-threaded execution semantics hold either way.
        self.exec_lock = make_lock("worker.exec")
        # Cancellation + deadline enforcement for tasks executing here
        # (cancel frames from the raylet, dcancel from direct callers,
        # and the deadline watchdog all funnel through it).
        self.cancel_registry = _CancelRegistry()
        self._rid = 0  # guard: _rid_lock
        self._rid_lock = make_lock("remote_worker.rid")
        self._pending: Dict[int, dict] = {}
        # Done-message coalescing for batched dispatch: while more tasks
        # wait in the local queue, done frames buffer and flush in ONE
        # sendall when the queue drains (or before any blocking request) —
        # each sendall to the busy raylet costs a scheduler wakeup.  A
        # background flusher bounds the staleness to ~2ms so a fast task's
        # result is never held hostage by a slow batch member running
        # behind it.
        self._done_buf: list = []  # guard: _done_lock
        self._done_lock = make_lock("remote_worker.done")
        self._done_pending = threading.Event()
        self._reader = threading.Thread(target=self._read_loop,
                                        name="worker-reader", daemon=True)
        self._reader.start()
        self._flusher = threading.Thread(target=self._flush_loop,
                                         name="worker-done-flush",
                                         daemon=True)
        self._flusher.start()

    def _flush_loop(self):
        while True:
            self._done_pending.wait()
            time.sleep(0.002)  # let a fast burst coalesce
            self._done_pending.clear()
            self.flush_dones()

    def _read_loop(self):
        # Buffered frame reader: a coalesced dispatch train from the raylet
        # costs ~one recv syscall total instead of two (header + payload)
        # per message.
        reader = protocol.FrameReader(self.sock)
        while True:
            try:
                msg = reader.recv_msg()
            except (OSError, protocol.ProtocolError):
                msg = None
            if msg is None:
                os._exit(0)  # raylet gone — die quietly
            t = msg.get("t")
            if t == "task":
                self.task_queue.put(msg)
            elif t == "exit_checkpoint":
                # graceful restart-allowed kill: drain queued calls, take
                # a final snapshot, then exit — handled on the EXECUTOR
                # thread (a snapshot mid-call would tear state)
                self.task_queue.put(msg)
            elif t == "reply":
                entry = self._pending.pop(msg["rid"], None)
                if entry is not None:
                    entry["msg"] = msg
                    entry["event"].set()
            elif t == "stack":
                # live introspection (`ray_tpu stack`): answered HERE on
                # the reader thread, so a worker stuck in user code — or
                # deadlocked on the executor — still reports every
                # thread's stack (the py-spy-dump analogue, in-process)
                from ray_tpu.util import profiling

                try:
                    self._send({"t": "stack_reply",
                                "token": msg.get("token"),
                                "pid": os.getpid(),
                                "threads": profiling.dump_threads(
                                    proc="worker")})
                except OSError:
                    pass
            elif t == "cancel":
                # cancel/deadline fan-out from the raylet: a queued task
                # is marked for the pre-exec check, a RUNNING one gets
                # the exception raised in its executor thread (handled
                # HERE on the reader thread — the executor is the thread
                # being interrupted)
                self.cancel_registry.cancel(
                    msg["task_id"],
                    DeadlineExceededError if msg.get("deadline")
                    else TaskCancelledError)
            elif t == "direct_lease":
                # lease grant/release notice: the DirectServer validates
                # lease hellos against this token (None = not leased)
                self.active_lease_id = msg.get("lease_id")
            elif t == "direct_fence":
                # the raylet fenced an actor/node we hold direct channels
                # to: tear down and reconcile in-flight calls via the
                # raylet path (handled on this reader thread — the
                # executor may be blocked inside one of those calls)
                if self._direct is not None:
                    self._direct.on_fence(msg)
            elif t == "shutdown":
                os._exit(0)

    def _send(self, msg):
        protocol.send_msg(self.sock, msg, self.send_lock)

    def send_done(self, msg):
        """Send a task-completion message, coalescing with neighbors while
        batched work is still queued locally (flushed at queue drain,
        before any blocking request, or by the ~2ms background flusher)."""
        # Hold announcements for refs this task deserialized must reach the
        # raylet BEFORE the done (which releases the spec's borrow pins) —
        # the socket preserves order, so flushing them first suffices.
        from ray_tpu.core.worker import flush_pending_releases

        flush_pending_releases()
        with self._done_lock:
            self._done_buf.append(msg)
            if not self.task_queue.empty():
                self._done_pending.set()
                return
            buf, self._done_buf = self._done_buf, []
        protocol.send_msgs(self.sock, buf, self.send_lock)

    def flush_dones(self):
        with self._done_lock:
            buf, self._done_buf = self._done_buf, []
        if buf:
            protocol.send_msgs(self.sock, buf, self.send_lock)

    def queue_done(self, msg):
        """Buffer a completion strictly for the background flusher (~2ms):
        used for direct_done notices — the CALLER already has the result,
        so the raylet's bookkeeping copy is latency-tolerant and must not
        cost this thread a per-call sendall."""
        from ray_tpu.core.worker import flush_pending_releases

        flush_pending_releases()  # hold events precede the done (in order)
        with self._done_lock:
            self._done_buf.append(msg)
            self._done_pending.set()

    def queue_direct_notes(self, notes):
        """Buffer a whole drained train of direct_running/direct_done
        notes as ONE direct_notes frame (burst mode): one ref-event
        flush and one done-buffer lock round per train instead of two
        per call — the raylet unpacks and applies them in order."""
        from ray_tpu.core.worker import flush_pending_releases

        flush_pending_releases()  # hold events precede the dones (in order)
        with self._done_lock:
            self._done_buf.append({"t": "direct_notes", "notes": notes})
            self._done_pending.set()

    def requeue_pending_tasks(self):
        """Hand unstarted batched tasks back to the raylet — called before
        blocking (nested get/wait): the current task may wait on work that
        would otherwise sit behind it in this worker's own queue.  Pool
        workers only — actor calls are pinned to their worker (and an actor
        worker's queue order must not be disturbed)."""
        if self.actor_instance is not None:
            return
        give_back = []
        keep = []
        try:
            while True:
                m = self.task_queue.get_nowait()
                if m.get("direct_conn") is not None or "spec" not in m:
                    # direct calls belong to their caller's channel, not
                    # the raylet — keep them queued here
                    keep.append(m)
                else:
                    give_back.append(m["spec"])
        except queue.Empty:
            pass
        for m in keep:
            self.task_queue.put(m)
        if give_back:
            self._send({"t": "requeue", "specs": give_back})

    def _request(self, op, _wait_timeout=None, **fields):
        """Round-trip to the raylet.  ``_wait_timeout`` bounds the local wait
        (used by get/wait with a user timeout): on expiry the request is
        cancelled raylet-side and TimeoutError raised here."""
        self.flush_dones()  # the raylet must see completions before we wait
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        entry = {"event": threading.Event(), "msg": None}
        self._pending[rid] = entry
        self._send({"t": "request", "rid": rid, "op": op, **fields})
        remaining = _wait_timeout
        if (op in ("get", "wait", "stream_next")
                and (remaining is None or remaining > 0.05)
                and not self.task_queue.empty()):
            # Grace period before handing batched tasks back: a get the
            # raylet satisfies immediately must not trigger a
            # requeue/redispatch churn cycle, and short-timeout POLLS
            # (wait(timeout=0) loops) never give the queue back at all —
            # only an actually-blocking request does.
            grace = 0.01 if remaining is None else min(0.01, remaining)
            if entry["event"].wait(grace):
                remaining = 0
            else:
                if remaining is not None:
                    remaining -= grace
                self.requeue_pending_tasks()
        if not entry["event"].wait(remaining):
            self._pending.pop(rid, None)
            self._send({"t": "request", "rid": rid + (1 << 62), "op":
                        "cancel_request", "target_rid": rid})
            raise TimeoutError(f"request {op} timed out")
        msg = entry["msg"]
        if not msg["ok"]:
            raise msg["error"]
        return msg["value"]


def _deliver_result(worker: RemoteWorker, msg: dict, done: dict):
    """Route a task's completion: relayed tasks send the ordinary done to
    the raylet; direct calls push the result STRAIGHT to the caller's
    channel (the latency path), remember it for retry dedup, and notify
    the raylet with a direct_done so object state / ref counting / task
    events / lineage stay exactly as on the relayed path."""
    dconn = msg.get("direct_conn")
    if dconn is None:
        worker.send_done(done)
        return
    spec: TaskSpec = msg["spec"]
    worker.direct_server.remember(spec.task_id, done)
    res = dict(done)
    res["t"] = "dresult"
    burst = config.direct_burst
    rx = msg.get("_rx_t")
    if burst and rx is not None:
        # decode→result turnover, stamped for the caller's lease
        # pipelining EWMA (burst mode only — the pre-burst dresult
        # stays byte-identical under the kill switch)
        res["dur"] = time.time() - rx
    dconn.send_result(res)
    note = dict(done)
    note["t"] = "direct_done"
    note["spec"] = spec
    if burst and rx is not None:
        # same stamp on the bookkeeping side: the raylet's FINISHED
        # event keeps exec latency when the RUNNING note is elided
        note["dur"] = res["dur"]
    if burst and msg.get("_inline"):
        # inline exec on the conn thread: the note coalesces into the
        # train's batched direct_notes flush (see _DirectConn.flush_notes)
        dconn.note_buf.append(note)
    else:
        worker.queue_done(note)


def _resolve_callable(worker: RemoteWorker, spec: TaskSpec, fn_blob):
    key = spec.function_id.binary() if spec.function_id else None
    if key is not None and key in worker._fn_cache:
        return worker._fn_cache[key]
    blob = fn_blob or spec.function_blob
    if blob is None and spec.function_id is not None:
        blob = worker._request("get_function", id=spec.function_id.binary())
    if blob is None:
        raise RuntimeError(f"no function payload for task {spec.name}")
    fn = cloudpickle.loads(blob)
    if key is not None:
        worker._fn_cache[key] = fn
    return fn


def _resolve_args(worker: RemoteWorker, spec: TaskSpec, arg_values):
    def resolve(entry):
        kind, payload = entry
        if kind == "v":
            return serialization.loads(payload)
        oid: ObjectID = payload
        blob = arg_values.get(oid.hex())
        if blob is not None:
            return serialization.loads(blob)
        if worker.store is None:
            raise RuntimeError("no object store attached")
        # evicted arg -> lineage reconstruction via the raylet
        return worker.read_store_object(oid)

    args = [resolve(a) for a in spec.args]
    kwargs = {k: resolve(v) for k, v in spec.kwargs}
    return args, kwargs


def _package_results(worker: RemoteWorker, spec: TaskSpec, result):
    inline: Dict[str, bytes] = {}
    stored = []
    if spec.num_returns in (1, STREAMING_RETURNS):
        values = [result]  # streaming: result is the completion marker
    else:
        values = list(result)
        if len(values) != spec.num_returns:
            raise ValueError(
                f"task {spec.name} declared num_returns={spec.num_returns} "
                f"but returned {len(values)} values"
            )
    sizes: Dict[str, int] = {}
    contains: Dict[str, list] = {}
    for oid, val in zip(spec.return_ids(), values):
        ser, inner = serialization.serialize_with_refs(val)
        if inner:
            # refs inside the result: the raylet pins them for the result
            # object's lifetime (borrow pinning)
            contains[oid.hex()] = inner
        n = ser.total_bytes()
        if n <= config.inline_object_max_bytes or worker.store is None:
            inline[oid.hex()] = ser.to_bytes()
        else:
            worker.store.put_serialized(oid, ser)
            stored.append(oid.hex())
            sizes[oid.hex()] = n
    return inline, stored, sizes, contains


def _save_checkpoint(worker: RemoteWorker):
    """Serialize the actor's ``__ray_save__()`` state into a fresh object
    and hand it to the raylet (inline blob, or shm store + size), which
    records it on the actor and replicates it.  Runs on the executor
    thread only — never concurrently with a method call."""
    inst = worker.actor_instance
    if inst is None:
        return
    from ray_tpu.core.ids import put_counter

    oid = put_counter.next_object_id()
    try:
        state = inst.__ray_save__()
        ser = serialization.serialize(state)
        n = ser.total_bytes()
        msg = {"t": "checkpoint", "actor_id": worker.current_actor_id,
               "seq": worker.checkpoint_seq + 1, "id": oid.hex()}
        if n <= config.inline_object_max_bytes or worker.store is None:
            msg["inline"] = ser.to_bytes()
        else:
            # inside the guard: a full store with spilling disabled
            # raises ObjectStoreFullError — skip the snapshot, don't
            # kill the actor
            worker.store.put_serialized(oid, ser)
            msg["size"] = n
    except Exception:  # noqa: BLE001 — a failed snapshot must not kill calls
        traceback.print_exc()
        return
    worker.checkpoint_seq += 1
    # completed results must reach the raylet BEFORE the snapshot that
    # includes their effects (socket order preserves the invariant)
    worker.flush_dones()
    worker._send(msg)


def _maybe_checkpoint(worker: RemoteWorker):
    """Count a completed actor call toward the checkpoint cadence."""
    if not worker.checkpoint_interval:
        return
    worker.checkpoint_calls += 1
    if worker.checkpoint_calls < worker.checkpoint_interval:
        return
    worker.checkpoint_calls = 0
    _save_checkpoint(worker)


def _run_streaming(worker: RemoteWorker, spec: TaskSpec, gen):
    """Drive a generator task: each yield ships to the raylet immediately
    (reference: streaming generator returns, `_raylet.pyx:224`) so consumers
    can read item i while item i+1 is still being produced.  The slot-0
    completion marker resolves to the item count."""
    idx = 0
    for item in gen:
        oid = spec.stream_item_id(idx)
        ser, inner = serialization.serialize_with_refs(item)
        n = ser.total_bytes()
        if n <= config.inline_object_max_bytes or worker.store is None:
            worker._send({"t": "stream_item", "id": oid.hex(), "index": idx,
                          "inline": ser.to_bytes(), "contains": inner})
        else:
            worker.store.put_serialized(oid, ser)
            worker._send({"t": "stream_item", "id": oid.hex(), "index": idx,
                          "inline": None, "size": n, "contains": inner})
        idx += 1
    return idx


def _apply_runtime_env(spec: TaskSpec):
    _, _, _, _, _, _rtenv, global_worker = _hot()
    _rtenv.ensure_runtime_env(global_worker(), spec.runtime_env)


def _enrich_control_error(e, spec: TaskSpec):
    """Async-raised interrupts come from PyThreadState_SetAsyncExc with
    the exception CLASS (instances are unreliable there), so a mid-exec
    DeadlineExceededError carries no message/hop — rebuild it with the
    task name and the worker.mid_exec hop before it rides to the
    caller."""
    if isinstance(e, DeadlineExceededError) and not e.hop:
        return DeadlineExceededError(
            f"task {spec.name} missed its deadline mid-execution",
            hop="worker.mid_exec")
    return e


def _preflight(worker: RemoteWorker, spec: TaskSpec):
    """Deadline + cancellation gate, run before any expensive phase
    (entry, between arg-pull and exec): work whose deadline already
    passed — or that a cancel frame reached first — raises the typed
    control error instead of executing (no wasted exec)."""
    worker.cancel_registry.check(spec.task_id)
    if (config.deadlines and spec.deadline is not None
            and time.time() > spec.deadline):
        raise DeadlineExceededError(
            f"task {spec.name} deadline expired before execution",
            hop="worker.pre_exec")


def _setup_actor_concurrency(worker: RemoteWorker, spec: TaskSpec):
    """After actor instantiation: start the thread pool / asyncio loop that
    back max_concurrency>1 and coroutine methods."""
    inst = worker.actor_instance
    # Walk the class MRO rather than getattr on the instance: getattr would
    # EXECUTE properties as a side effect of actor creation.
    has_async = any(
        inspect.iscoroutinefunction(v)
        for klass in type(inst).__mro__
        for v in vars(klass).values()
    )
    if has_async and worker.actor_loop is None:
        loop = asyncio.new_event_loop()
        threading.Thread(target=loop.run_forever, daemon=True,
                         name="actor-asyncio").start()
        worker.actor_loop = loop
    if spec.concurrency_groups and worker.group_executors is None:
        if has_async:
            raise NotImplementedError(
                "concurrency_groups are thread-pool based and do not "
                "combine with asyncio actor methods — use one or the "
                "other (reference async fiber groups are not implemented)")
        # One thread pool per named group (reference: threaded concurrency
        # groups, `concurrency_group_manager.cc`): each group's limit is
        # enforced by its pool size; the raylet additionally admits per
        # group.
        worker.group_executors = {
            name: ThreadPoolExecutor(
                max_workers=n, thread_name_prefix=f"actor-{name}")
            for name, n in spec.concurrency_groups.items()
        }
    elif spec.max_concurrency > 1 and worker.actor_executor is None:
        worker.actor_executor = ThreadPoolExecutor(
            max_workers=spec.max_concurrency, thread_name_prefix="actor-exec"
        )


class _run_span:
    """Shared task.run tracing wrapper for the sync and asyncio execution
    paths (child span of the submit-side span; reference:
    `_inject_tracing_into_function`, `tracing_helper.py:322`).  Call
    ``done(ok)`` with the inner result so user exceptions converted into
    error replies still mark the span ERROR.

    Only requests carrying a submit-side context get an execution span
    (with no ctx a span here would mint a fresh root per execution —
    noise, not a request trace), and SAMPLED-OUT requests skip the span
    object entirely: a failure is reported post-hoc as one synthesized
    ERROR span under the propagated ids, so errored requests stay
    visible while the other 99% pay ~nothing."""

    def __init__(self, spec: TaskSpec):
        tracing = _hot()[4]
        self._sp = None
        self._err_ctx = None
        ctx = spec.trace_ctx
        if ctx is None or not tracing.tracing_enabled():
            return
        if ctx.get("sampled", True):
            self._sp = tracing.span(
                f"task.run {spec.name}", parent=ctx,
                task_id=spec.task_id.hex(), kind=spec.kind)
        else:
            self._err_ctx = ctx
            self._name = spec.name
            self._task_id = spec.task_id.hex()

    def __enter__(self):
        if self._sp is not None:
            self._sp.__enter__()
        elif self._err_ctx is not None:
            self._t0 = time.time()
        return self

    def done(self, ok: bool):
        if ok:
            return
        if self._sp is not None:
            self._sp.set_error("task raised (see error object)")
        elif self._err_ctx is not None:
            from ray_tpu.util import tracing

            tracing.emit_span(
                f"task.run {self._name}", self._err_ctx["trace_id"],
                self._err_ctx.get("span_id"), self._t0, time.time(),
                status="ERROR", error="task raised (see error object)",
                task_id=self._task_id)

    def __exit__(self, *exc):
        if self._sp is not None:
            return self._sp.__exit__(*exc)
        return False


async def _execute_async(worker: RemoteWorker, msg: dict):
    with _run_span(msg["spec"]) as rs:
        rs.done(await _execute_async_inner(worker, msg))


async def _execute_async_inner(worker: RemoteWorker, msg: dict) -> bool:
    spec: TaskSpec = msg["spec"]
    from ray_tpu.runtime_context import (
        _current_deadline,
        _current_task_id,
    )
    from ray_tpu.util import profiling, tracing

    _ctx_token = _current_task_id.set(spec.task_id)
    _dl_token = _current_deadline.set(
        spec.deadline if config.deadlines else None)
    # Profiler attribution (best-effort on the shared asyncio thread:
    # interleaved calls each stamp the loop thread while they hold it;
    # chain=False so an out-of-LIFO-order exit clears instead of
    # restoring a finished task's tags).
    _ptags = profiling.set_task_tags(
        task_id=spec.task_id.hex(),
        trace_id=(spec.trace_ctx or {}).get("trace_id"),
        actor_id=spec.actor_id.hex() if spec.actor_id else None,
        name=spec.name, chain=False)
    try:
        with tracing.maybe_span("worker.get_args"):
            args, kwargs = _resolve_args(worker, spec,
                                         msg.get("arg_values", {}))
        # Async calls: pre-exec check, then register the asyncio task so
        # mid-exec cancel/deadline can task.cancel() it on the loop
        # (CancelledError at the next await — an async exception into
        # the shared loop thread would kill every interleaved call).
        _preflight(worker, spec)
        from ray_tpu.util import chaos as _chaos

        _chaos.exec_delay(spec.name)
        _preflight(worker, spec)
        worker.cancel_registry.register_async(
            spec.task_id, asyncio.get_running_loop(),
            asyncio.current_task(),
            spec.deadline if config.deadlines else None)
        try:
            with tracing.maybe_span("worker.exec"):
                result = await getattr(
                    worker.actor_instance, spec.method_name)(*args, **kwargs)
        except asyncio.CancelledError:
            # our cancel()/watchdog cancelled the task: convert back to
            # the typed control error the caller dispatches on (the
            # outer handler delivers it as the done frame)
            exc = worker.cancel_registry.cancelled_as(spec.task_id)
            raise (exc or TaskCancelledError)() from None
        finally:
            worker.cancel_registry.deregister(spec.task_id)
        with tracing.maybe_span("worker.result_push"):
            inline, stored, sizes, contains = _package_results(worker, spec,
                                                               result)
            _deliver_result(worker, msg,
                            {"t": "done", "task_id": spec.task_id,
                             "ok": True, "inline": inline, "stored": stored,
                             "sizes": sizes, "contains": contains})
        return True
    except CONTROL_ERRORS as e:
        # typed control-flow errors reach the caller AS-IS (a TaskError
        # wrapper would hide the type the router/get() dispatch on)
        _deliver_result(worker, msg, {
            "t": "done", "task_id": spec.task_id, "ok": False,
            "error": _enrich_control_error(e, spec), "retryable": False,
        })
        return False
    except Exception:  # noqa: BLE001
        tb = traceback.format_exc()
        err = TaskError(spec.name, tb, None)
        _deliver_result(worker, msg, {
            "t": "done", "task_id": spec.task_id, "ok": False,
            "error": err, "retryable": spec.retry_exceptions,
        })
        return False
    finally:
        profiling.reset_task_tags(_ptags)
        _current_deadline.reset(_dl_token)
        _current_task_id.reset(_ctx_token)


def execute_task(worker: RemoteWorker, msg: dict):
    dconn = msg.get("direct_conn")
    if dconn is not None:
        # the raylet never saw this call dispatch: a batched RUNNING note
        # keeps the timeline / state API seeing in-flight direct work
        # (rides the ~2ms done-flusher, not the latency path)
        note = {"t": "direct_running", "spec": msg["spec"]}
        if dconn.coalesce and config.direct_burst:
            # mid-train inline exec: batch the note with its direct_done
            # into the train's one direct_notes frame.  Head-of-train and
            # queue-path calls keep the per-call note so a LONG direct
            # call is still visible (and raylet-cancellable) mid-exec.
            dconn.note_buf.append(note)
        else:
            worker.queue_done(note)
    with _run_span(msg["spec"]) as rs:
        ok = _execute_task_inner(worker, msg)
        rs.done(ok)
        if msg["spec"].kind == ACTOR_TASK:
            # cadence counts COMPLETED calls (ok or errored — either may
            # have mutated state); __ray_terminate__ never returns here
            _maybe_checkpoint(worker)
        return ok


def _execute_task_inner(worker: RemoteWorker, msg: dict):
    spec: TaskSpec = msg["spec"]
    _current_deadline, _current_task_id, _chaos, profiling, tracing, _, _ \
        = _hot()
    _ctx_token = _current_task_id.set(spec.task_id)
    _dl_token = _current_deadline.set(
        spec.deadline if config.deadlines else None)
    # Profiler attribution: samples taken on this thread while the task
    # runs fold under its task/trace/actor ids (flamegraph slicing).
    _ptags = profiling.set_task_tags(
        task_id=spec.task_id.hex(),
        trace_id=(spec.trace_ctx or {}).get("trace_id"),
        actor_id=spec.actor_id.hex() if spec.actor_id else None,
        name=spec.name)
    extra: dict = {}
    _registered = False
    try:
        if msg.get("__bad_group__") is not None:
            raise ValueError(
                f"undeclared concurrency group "
                f"{msg['__bad_group__']!r} for {spec.name}")
        _apply_runtime_env(spec)
        _preflight(worker, spec)
        with tracing.maybe_span("worker.get_args"):
            args, kwargs = _resolve_args(worker, spec,
                                         msg.get("arg_values", {}))
        # between arg-pull and exec: the deadline/cancel gate, then the
        # chaos slow-executor seam, then gate again — an injected delay
        # must be visible to the deadline check like real slowness
        _preflight(worker, spec)
        _chaos.exec_delay(spec.name)
        _preflight(worker, spec)
        worker.cancel_registry.register(
            spec.task_id, threading.get_ident(),
            spec.deadline if config.deadlines else None)
        _registered = True
        with tracing.maybe_span("worker.exec"):
            if spec.kind == ACTOR_CREATION_TASK:
                cls = _resolve_callable(worker, spec, msg.get("fn_blob"))
                worker.actor_instance = cls(*args, **kwargs)
                worker.current_actor_id = spec.actor_id
                # direct-transport fencing: hellos must present this exact
                # restart generation (stamped by the owning raylet)
                worker.actor_generation = getattr(
                    spec, "_direct_generation", 0)
                _setup_actor_concurrency(worker, spec)
                worker.checkpoint_interval = spec.checkpoint_interval or 0
                if worker.checkpoint_interval \
                        and worker.actor_loop is not None:
                    # the options-time validation can't see coroutine
                    # methods; fail creation loudly rather than
                    # snapshot-while-awaiting
                    raise ValueError(
                        "checkpoint_interval is not supported on asyncio "
                        "actors (state may mutate at await points during "
                        "__ray_save__)")
                if spec.restore_oid is not None:
                    # warm restart: re-hydrate from the latest checkpoint
                    # the owning raylet attached to this (re)creation —
                    # spanned as a recovery event under the restarting
                    # request's trace
                    with tracing.maybe_span(
                            "recovery.restore",
                            checkpoint=spec.restore_oid.hex()):
                        blob = msg.get("arg_values", {}).get(
                            spec.restore_oid.hex())
                        state = (serialization.loads(blob)
                                 if blob is not None
                                 else worker.read_store_object(
                                     spec.restore_oid))
                        worker.actor_instance.__ray_restore__(state)
                    extra["restored"] = True
                # the raylet pipelines calls only to sync actors — report
                # the execution model it can't otherwise see
                extra["async_actor"] = worker.actor_loop is not None
                result = None
            elif spec.kind == ACTOR_TASK:
                if spec.method_name == "__ray_terminate__":
                    worker.flush_dones()
                    worker._send({"t": "done", "task_id": spec.task_id,
                                  "ok": True,
                                  "inline": {spec.return_ids()[0].hex():
                                             serialization.dumps(None)},
                                  "stored": []})
                    os._exit(0)
                inst = worker.actor_instance
                if inst is None:
                    raise RuntimeError("actor instance missing")
                method = getattr(inst, spec.method_name)
                result = method(*args, **kwargs)
                if inspect.iscoroutine(result):
                    # Coroutine reached the sync path (e.g. called from an
                    # executor thread): run it on the actor loop to
                    # completion.
                    result = asyncio.run_coroutine_threadsafe(
                        result, worker.actor_loop
                    ).result() if worker.actor_loop else asyncio.run(result)
            else:
                fn = _resolve_callable(worker, spec, msg.get("fn_blob"))
                result = fn(*args, **kwargs)
            if spec.num_returns == STREAMING_RETURNS:
                result = _run_streaming(worker, spec, result)
        # out of the interruptible window BEFORE packaging results: a
        # deadline/cancel exception landing mid-push could double-report
        worker.cancel_registry.deregister(spec.task_id)
        _registered = False
        with tracing.maybe_span("worker.result_push"):
            inline, stored, sizes, contains = _package_results(worker, spec,
                                                               result)
            _deliver_result(worker, msg,
                            {"t": "done", "task_id": spec.task_id,
                             "ok": True, "inline": inline, "stored": stored,
                             "sizes": sizes, "contains": contains, **extra})
        return True
    except CONTROL_ERRORS as e:
        # deadline expiry / cancellation / backpressure reach the caller
        # TYPED (a TaskError wrapper would hide what get() dispatches on)
        # and never retry
        _deliver_result(worker, msg, {
            "t": "done", "task_id": spec.task_id, "ok": False,
            "error": _enrich_control_error(e, spec), "retryable": False,
        })
        return False
    except Exception as e:  # noqa: BLE001
        tb = traceback.format_exc()
        err = TaskError(spec.name, tb, None)
        _deliver_result(worker, msg, {
            "t": "done", "task_id": spec.task_id, "ok": False,
            "error": err, "retryable": spec.retry_exceptions,
        })
        return False
    finally:
        if _registered:
            try:
                worker.cancel_registry.deregister(spec.task_id)
            except CONTROL_ERRORS:
                # a cancel frame raced the error path's own deregister:
                # the async exception fired while we were already
                # unwinding (done frame sent) — absorb it here so it
                # cannot escape into the executor / direct-conn loop
                pass
        profiling.reset_task_tags(_ptags)
        _current_deadline.reset(_dl_token)
        _current_task_id.reset(_ctx_token)


class _PrefixStream:
    """Line-prefixing stdout/stderr wrapper — the lightweight analogue of
    the reference's log monitor pipeline (worker log files tailed by
    `log_monitor.py:102` and re-printed on the driver with a
    ``(pid=..)`` prefix).  Workers inherit the driver's stdio here, so
    prefixing at the source gives the same attribution."""

    def __init__(self, stream, prefix: str):
        self._stream = stream
        self._prefix = prefix
        self._at_line_start = True

    def write(self, data: str):
        if not data:
            return 0
        out = []
        for chunk in data.splitlines(keepends=True):
            if self._at_line_start:
                out.append(self._prefix)
            out.append(chunk)
            self._at_line_start = chunk.endswith("\n")
        self._stream.write("".join(out))
        return len(data)

    def flush(self):
        self._stream.flush()

    def __getattr__(self, name):
        return getattr(self._stream, name)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--socket", required=True)
    parser.add_argument("--store", default=None)
    args = parser.parse_args()

    # Crash forensics: SIGSEGV/SIGBUS/SIGABRT dump every thread's stack to
    # stderr — which cluster mode redirects to this worker's log file, so
    # the dump lands in the excerpt the raylet attaches to the failure.
    import faulthandler

    faulthandler.enable()

    if config.log_to_driver:
        prefix = f"(worker pid={os.getpid()}) "
        sys.stdout = _PrefixStream(sys.stdout, prefix)
        sys.stderr = _PrefixStream(sys.stderr, prefix)

    from ray_tpu.util import profiling, tracing

    tracing.set_process_label("worker")
    tracing.maybe_enable_from_env()
    profiling.ensure_profiler("worker")

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(args.socket)
    worker = RemoteWorker(sock)
    if args.store:
        worker.store = ShmObjectStore(args.store)
    init_worker(worker)
    if config.direct_calls:
        # Direct transport, both roles: serve direct calls addressed to
        # this worker (listener address rides the register message), and
        # dial peers for this worker's own nested actor calls / leases.
        from ray_tpu.core.direct import DirectCallClient, DirectServer

        try:
            worker.direct_server = DirectServer(
                worker, os.path.dirname(os.path.abspath(args.socket)))
        except OSError:
            worker.direct_server = None  # unservable dir: relayed only
        worker._direct = DirectCallClient(
            worker,
            broker=lambda aid: worker._request("direct_lookup",
                                               actor_id=aid),
            resubmit=worker._submit_relayed,
            lease=lambda spec: worker._request("direct_lease", spec=spec),
            lease_release=lambda lid: worker._request(
                "direct_lease_release", lease_id=lid),
        )
    worker._send({
        "t": "register",
        "pid": os.getpid(),
        "worker_id": worker.worker_id,
        "profile": config.worker_profile or "cpu",
        "direct_addr": (worker.direct_server.addr
                        if worker.direct_server is not None else None),
    })
    if tracing.tracing_enabled():
        # span export: batches ride the control socket to the raylet,
        # which forwards to the GCS trace table on its flush cadence
        tracing.set_flush_target(
            lambda spans, dropped: worker._send(
                {"t": "spans", "spans": spans, "dropped": dropped}))
    # folded profile export rides the same route (raylet -> GCS profile
    # table); registered unconditionally — RAY_TPU_PROFILE is a live
    # switch, so a worker started with profiling off must still ship
    # samples once it's flipped on
    profiling.set_flush_target(
        lambda samples, dropped: worker._send(
            {"t": "profile_samples", "samples": samples,
             "dropped": dropped}))
    # metric time-series delta points ride the same route (raylet -> GCS
    # metrics table); registered unconditionally — the per-process flusher
    # only spins up once a metric is registered in this worker, and the
    # flush itself checks the metrics_history flag
    from ray_tpu.util import metrics as _metrics_mod

    _metrics_mod.set_points_target(
        lambda points, dropped: worker._send(
            {"t": "metric_points", "points": points, "dropped": dropped}))
    while True:
        try:
            _main_tick(worker)
        except CONTROL_ERRORS:
            # a mid-exec cancel/deadline exception that lost the race with
            # task completion lands here, between tasks — absorb it; the
            # task it was aimed at already reported
            continue


def _main_tick(worker: RemoteWorker):
    msg = worker.task_queue.get()
    if msg.get("t") == "exit_checkpoint":
        # restart-allowed kill: final snapshot (queued calls ahead of
        # this message already ran and are counted in it), then exit —
        # the raylet restarts the actor from this exact state.
        if worker.checkpoint_interval:
            _save_checkpoint(worker)
        worker.flush_dones()
        os._exit(0)
    spec: TaskSpec = msg["spec"]
    if (worker.direct_server is not None
            and msg.get("direct_conn") is None):
        cached, deferred = worker.direct_server.reconcile_probe(
            spec.task_id)
        if cached is not None:
            # raylet-path reconcile of a direct call that ALREADY
            # executed here: re-send the recorded result — executing
            # again would double the call's side effects
            cached["t"] = "done"
            cached["task_id"] = spec.task_id
            worker.send_done(cached)
            return
        if deferred:
            # the ORIGINAL direct execution is still in flight (e.g.
            # a false-SUSPECT fence made the caller reconcile while
            # the callee kept running): remember() answers this
            # dispatch with the recorded result at completion —
            # executing now would double the call's side effects
            return
    if (spec.kind == ACTOR_TASK and worker.actor_instance is not None
            and spec.method_name != "__ray_terminate__"):
        # getattr_static on the INSTANCE: side-effect-free (no property
        # getters run on the dispatch thread — the hazard
        # _setup_actor_concurrency documents) AND it sees instance-dict
        # methods (self.handler = some_async_fn) that a type()-level
        # lookup would miss, silently demoting them to the blocking
        # sync path.  Static lookup returns raw descriptors, so unwrap
        # them or an async staticmethod would fail the coroutine check.
        method = inspect.getattr_static(
            worker.actor_instance, spec.method_name, None)
        if isinstance(method, (staticmethod, classmethod)):
            method = method.__func__
        if worker.actor_loop is not None and \
                inspect.iscoroutinefunction(method):
            # Async actor: schedule on the loop, keep draining the queue
            # — calls interleave at await points (up to max_concurrency
            # in flight, bounded raylet-side).
            asyncio.run_coroutine_threadsafe(
                _execute_async(worker, msg), worker.actor_loop
            )
            return
        if worker.group_executors is not None:
            group = spec.concurrency_group
            if group is None and method is not None:
                group = getattr(method, "__ray_tpu_method_options__",
                                {}).get("concurrency_group")
            pool = worker.group_executors.get(group or "_default")
            if pool is None:
                # undeclared group name: fail the CALL loudly (typos
                # must not silently serialize onto the default pool)
                msg["__bad_group__"] = group
                pool = worker.group_executors["_default"]
            pool.submit(execute_task, worker, msg)
            return
        if worker.actor_executor is not None:
            worker.actor_executor.submit(execute_task, worker, msg)
            return
    with worker.exec_lock:
        execute_task(worker, msg)


if __name__ == "__main__":
    main()
