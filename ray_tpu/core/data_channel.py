"""Zero-copy raylet-to-raylet data plane.

A DEDICATED TCP connection per peer pair carries bulk object bytes so
control traffic (heartbeats, task dispatch, done messages) never queues
behind megabytes of data frames — the reference runs object transfer as
chunked gRPC streams on the object manager's own channel pool
(`src/ray/object_manager/object_manager.h:117`), separate from the raylet's
control RPCs.

Wire format (little-endian, NO pickle anywhere on this channel):

  connect preamble   8 bytes  b"RTDP\\x02\\0\\0\\0"
  hello (pull side -> holder)      _HELLO: incarnation u64 | node_id 32s
      identity + fencing: a channel presenting an incarnation the cluster
      declared dead is refused (split-brain guard — a resurrected
      partitioned node must re-register before it may move bytes)
  request  (pull side -> holder)   _REQ:  op u8 | rid u64 | offset u64 |
                                          length u64 | object_id 20s
      op 1 = META   (offset/length ignored; reply carries the total size)
      op 2 = READ   (stream bytes [offset, offset+length) back)
  response (holder -> pull side)   _RESP: flags u8 | rid u64 | offset u64 |
                                          length u64 | [payload length bytes]
      flags 0 = DATA (payload = the requested range, complete)
            1 = META (length = total object size, no payload)
            2 = ERR  (payload = UTF-8 error message)

Zero copies end to end: the serving side writes straight off a pinned
``memoryview`` of the shm arena via ``sendmsg`` (spilled objects via
``os.sendfile``), and the receiving side ``recv_into``s directly into the
destination ``store.create()`` buffer — no ``bytes()`` slicing, no pickle
frame, no intermediate bytearray.

The channel is deliberately dumb: all policy (admission, striping across
holders, retry/rotation) lives in ``ray_tpu/core/pull_manager.py``.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from ray_tpu.core.ids import ObjectID
from ray_tpu.core.protocol import recv_exact as _recv_exact
from ray_tpu.core.protocol import recv_into_exact
from ray_tpu.util import chaos as _chaos
from ray_tpu.util.locks import make_lock

MAGIC = b"RTDP\x02\x00\x00\x00"

_REQ = struct.Struct("<BQQQ20s")
_RESP = struct.Struct("<BQQQ")
# connection hello: the pull side's identity + registration incarnation
# (node_id as 32 hex bytes; fencing input for the serving side)
_HELLO = struct.Struct("<Q32s")

OP_META = 1
OP_READ = 2

FLAG_DATA = 0
FLAG_META = 1
FLAG_ERR = 2

# sendfile granularity for spilled objects (bounds one syscall's worth of
# disk->socket work; the kernel loops internally anyway).
_SENDFILE_CHUNK = 8 << 20


def _send_header_and_view(sock: socket.socket, header: bytes, view) -> None:
    """One gather write for header + payload (``sendmsg``), falling back to
    a plain loop on partial sends.  ``view`` aliases the shm arena — the
    kernel copies straight out of the store, no user-space staging."""
    total = len(header) + len(view)
    sent = sock.sendmsg([header, view])
    if sent == total:
        return
    # Partial send (full socket buffer): finish with sendall on the rest.
    if sent < len(header):
        sock.sendall(header[sent:])
        sock.sendall(view)
    else:
        sock.sendall(view[sent - len(header):])


class DataServer:
    """Accepts peer data connections and serves META/READ requests straight
    from this node's shm store (or its spill directory).

    Each accepted connection gets one daemon thread (bounded by cluster
    size: peers keep ONE data connection per pair).  Serving never touches
    raylet event-thread state — only the thread-safe store client — so a
    slow or stalled peer can never head-of-line-block the control plane.
    """

    def __init__(self, node_ip: str, store_fn: Callable[[], object],
                 fence_fn: Optional[Callable[[str, int], bool]] = None):
        """``fence_fn(node_id, incarnation) -> bool``: incarnation-fencing
        check for the connect hello — False refuses the connection (the
        peer presented an incarnation that was declared dead)."""
        self._store_fn = store_fn
        self._fence_fn = fence_fn
        self._listener = socket.create_server((node_ip, 0), backlog=32)
        self.port = self._listener.getsockname()[1]
        self._conns: Dict[int, socket.socket] = {}  # guard: _lock
        self._lock = make_lock("data_server.conns")
        self._closed = False
        # Test seam: per-READ artificial delay (lets tests kill a holder
        # deterministically "mid-stream").
        self.serve_delay_s = 0.0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="data-accept", daemon=True)
        self._accept_thread.start()

    # ---- accept / serve ---------------------------------------------------

    def _accept_loop(self):
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            with self._lock:
                if self._closed:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return
                self._conns[sock.fileno()] = sock
            threading.Thread(target=self._serve_conn, args=(sock,),
                             name="data-serve", daemon=True).start()

    def _serve_conn(self, sock: socket.socket):
        key = sock.fileno()
        blackholed = False  # chaos partition: requests drain, replies vanish
        try:
            magic = _recv_exact(sock, len(MAGIC))
            if magic is None or bytes(magic) != MAGIC:
                return
            hello = _recv_exact(sock, _HELLO.size)
            if hello is None:
                return
            incarnation, peer_id_raw = _HELLO.unpack(bytes(hello))
            peer_id = peer_id_raw.rstrip(b"\x00").decode("ascii", "replace")
            if (self._fence_fn is not None
                    and not self._fence_fn(peer_id, incarnation)):
                return  # fenced incarnation: refuse to move bytes for it
            while not self._closed:
                hdr = _recv_exact(sock, _REQ.size)
                if hdr is None:
                    return
                op, rid, offset, length, oid_bytes = _REQ.unpack(bytes(hdr))
                oid = ObjectID(oid_bytes)
                if not blackholed:
                    fault = _chaos.net_fault("data", peer=peer_id,
                                             direction="in")
                    if fault == "blackhole":
                        blackholed = True
                    if fault is not None:
                        continue  # this response is swallowed by the chaos
                else:
                    continue
                if op == OP_META:
                    self._serve_meta(sock, rid, oid)
                elif op == OP_READ:
                    if self.serve_delay_s:
                        import time

                        time.sleep(self.serve_delay_s)
                    if self._closed:
                        return
                    self._serve_read(sock, rid, oid, offset, length)
                else:
                    self._send_err(sock, rid, f"unknown op {op}")
        except OSError:
            pass
        finally:
            with self._lock:
                self._conns.pop(key, None)
            try:
                sock.close()
            except OSError:
                pass

    def _send_err(self, sock, rid: int, msg: str):
        payload = msg.encode("utf-8", "replace")
        sock.sendall(_RESP.pack(FLAG_ERR, rid, 0, len(payload)) + payload)

    def _object_size(self, store, oid: ObjectID) -> Optional[int]:
        buf = store.get_buffer(oid)
        if buf is not None:
            try:
                return len(buf)
            finally:
                del buf
                store.release(oid)
        if store.has_spilled(oid):
            try:
                return os.stat(store._spill_path(oid)).st_size
            except OSError:
                return None
        return None

    def _serve_meta(self, sock, rid: int, oid: ObjectID):
        store = self._store_fn()
        size = self._object_size(store, oid) if store is not None else None
        if size is None:
            self._send_err(sock, rid, f"object {oid.hex()} not here")
            return
        sock.sendall(_RESP.pack(FLAG_META, rid, 0, size))

    def _serve_read(self, sock, rid: int, oid: ObjectID,
                    offset: int, length: int):
        store = self._store_fn()
        buf = store.get_buffer(oid) if store is not None else None
        if buf is not None:
            try:
                if offset + length > len(buf):
                    self._send_err(
                        sock, rid,
                        f"range [{offset},{offset + length}) out of bounds "
                        f"for {oid.hex()} ({len(buf)} bytes)")
                    return
                _send_header_and_view(
                    sock, _RESP.pack(FLAG_DATA, rid, offset, length),
                    buf[offset:offset + length])
            finally:
                del buf
                store.release(oid)
            return
        if store is not None and store.has_spilled(oid):
            self._serve_read_spilled(sock, rid, oid, offset, length, store)
            return
        self._send_err(sock, rid, f"object {oid.hex()} not here")

    def _serve_read_spilled(self, sock, rid: int, oid: ObjectID,
                            offset: int, length: int, store):
        try:
            fd = os.open(store._spill_path(oid), os.O_RDONLY)
        except OSError:
            self._send_err(sock, rid, f"object {oid.hex()} freed")
            return
        try:
            size = os.fstat(fd).st_size
            if offset + length > size:
                self._send_err(
                    sock, rid,
                    f"range [{offset},{offset + length}) out of bounds "
                    f"for spilled {oid.hex()} ({size} bytes)")
                return
            sock.sendall(_RESP.pack(FLAG_DATA, rid, offset, length))
            pos, remaining = offset, length
            while remaining > 0:
                try:
                    n = os.sendfile(sock.fileno(), fd, pos,
                                    min(remaining, _SENDFILE_CHUNK))
                except OSError:  # non-sendfile-able fs: plain read loop
                    with os.fdopen(os.dup(fd), "rb", closefd=True) as f:
                        f.seek(pos)
                        while remaining > 0:
                            data = f.read(min(remaining, _SENDFILE_CHUNK))
                            if not data:
                                raise OSError("spill file truncated")
                            sock.sendall(data)
                            pos += len(data)
                            remaining -= len(data)
                    return
                if n == 0:
                    raise OSError("sendfile returned 0")
                pos += n
                remaining -= n
        finally:
            os.close(fd)

    def close(self):
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class DataChannel:
    """Pull-side endpoint of one peer-pair data connection.

    ``request_range(rid, oid, offset, length, sink)`` registers a
    destination memoryview for ``rid`` and sends the READ; the receiver
    thread ``recv_into``s the response payload straight into that view.
    Events (data complete / meta / error / channel closed) are delivered
    via the ``on_event(channel, rid, kind, arg)`` callback FROM THE
    RECEIVER THREAD — the pull manager is responsible for its own locking
    and for hopping completions onto the raylet event loop.
    """

    def __init__(self, node_id: str, address: Tuple[str, int],
                 on_event: Callable[["DataChannel", Optional[int], str,
                                     object], None],
                 connect_timeout: float = 3.0,
                 identity: Optional[Tuple[str, int]] = None):
        """``identity``: this (pulling) node's ``(node_id, incarnation)``,
        sent in the connect hello for the server's fencing check."""
        self.node_id = node_id
        self._on_event = on_event
        self._sock = socket.create_connection(address,
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        try:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        my_id, my_inc = identity or ("", 0)
        self._sock.sendall(MAGIC + _HELLO.pack(
            int(my_inc), my_id.encode("ascii", "replace")[:32].ljust(
                32, b"\x00")))
        self._send_lock = make_lock("data_channel.send")
        self._sinks: Dict[int, memoryview] = {}  # guard: _sinks_lock
        self._sinks_lock = make_lock("data_channel.sinks")
        self._chaos_blackholed = False
        self.alive = True
        self._recv_thread = threading.Thread(
            target=self._recv_loop, name=f"data-recv-{node_id[:8]}",
            daemon=True)
        self._recv_thread.start()

    # ---- requests (any thread) -------------------------------------------

    def request_meta(self, rid: int, oid: ObjectID) -> bool:
        return self._send(_REQ.pack(OP_META, rid, 0, 0, oid.binary()))

    def request_range(self, rid: int, oid: ObjectID, offset: int,
                      length: int, sink: Optional[memoryview]) -> bool:
        """``sink`` must be exactly ``length`` bytes (or None to receive
        into a throwaway buffer — used when the store had no room and the
        caller accumulates via on_event)."""
        if sink is not None:
            with self._sinks_lock:
                self._sinks[rid] = sink
        ok = self._send(_REQ.pack(OP_READ, rid, offset, length, oid.binary()))
        if not ok and sink is not None:
            with self._sinks_lock:
                self._sinks.pop(rid, None)
        return ok

    def cancel(self, rid: int):
        """Forget a rid: bytes that still arrive for it are drained and
        dropped (keeps the stream framing intact after a reassignment)."""
        with self._sinks_lock:
            self._sinks.pop(rid, None)

    def _send(self, data: bytes) -> bool:
        if not self.alive:
            return False
        if self._chaos_blackholed:
            return True  # partitioned: the request silently vanishes
        fault = _chaos.net_fault("data", peer=self.node_id)
        if fault is not None:
            if fault == "blackhole":
                self._chaos_blackholed = True
            # dropped request: the pull watchdog rotates/retries the range
            return True
        try:
            with self._send_lock:
                # blocking-ok: the send lock EXISTS to serialize writers on
                # this socket; requests are tiny (37B) and the receiver
                # drains continuously, so the buffer can't stay full.
                self._sock.sendall(data)
            return True
        except OSError:
            self.close()
            return False

    # ---- receiver thread --------------------------------------------------

    def _recv_loop(self):
        try:
            self._recv_loop_inner()
        except OSError:
            pass  # reset/shutdown: same as EOF
        self.close()
        self._on_event(self, None, "closed", None)

    def _recv_loop_inner(self):
        sock = self._sock
        scratch = None
        while True:
            hdr = _recv_exact(sock, _RESP.size)
            if hdr is None:
                break
            flags, rid, offset, length = _RESP.unpack(bytes(hdr))
            if flags == FLAG_META:
                self._on_event(self, rid, "meta", length)
                continue
            if flags == FLAG_ERR:
                payload = _recv_exact(sock, length)
                if payload is None:
                    break
                self._on_event(self, rid, "err",
                               bytes(payload).decode("utf-8", "replace"))
                continue
            # DATA: land the payload in the registered sink (zero-copy), or
            # drain it if the rid was cancelled/reassigned.
            with self._sinks_lock:
                sink = self._sinks.pop(rid, None)
            if sink is not None and len(sink) == length:
                if not recv_into_exact(sock, sink):
                    break
                self._on_event(self, rid, "data", (offset, length))
            else:
                if sink is not None:
                    # length mismatch: protocol desync — treat as fatal
                    self.close()
                    break
                if scratch is None or len(scratch) < min(length, 1 << 20):
                    scratch = bytearray(min(max(length, 1), 1 << 20))
                remaining = length
                ok = True
                view = memoryview(scratch)
                while remaining > 0:
                    n = min(remaining, len(scratch))
                    if not recv_into_exact(sock, view[:n]):
                        ok = False
                        break
                    remaining -= n
                if not ok:
                    break

    def close(self):
        self.alive = False
        # shutdown() BEFORE close(): a receiver thread blocked in recv()
        # holds its own reference to the socket, so a bare close() would
        # never wake it and the "closed" event (which drives range
        # reassignment and pull failure) would never fire.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._sinks_lock:
            self._sinks.clear()

    def join_receiver(self, timeout: float = 1.0):
        """Wait for the receiver thread to exit (no-op from the receiver
        thread itself).  Used to quiesce writes into a destination buffer
        before its allocation is freed."""
        th = self._recv_thread
        if th is not threading.current_thread():
            th.join(timeout)
