"""``@ray_tpu.remote`` functions (reference: `python/ray/remote_function.py`)."""

from __future__ import annotations

import copy
import time
from typing import Any, Dict, Optional

from ray_tpu.core.config import config
from ray_tpu.core.ids import TaskID
from ray_tpu.core.task_spec import NORMAL_TASK, TaskSpec
from ray_tpu.core.worker import global_worker
from ray_tpu.util.tracing import submit_with_span


def _build_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    num_cpus = opts.get("num_cpus")
    num_tpus = opts.get("num_tpus", opts.get("num_gpus"))
    res["CPU"] = float(1 if num_cpus is None else num_cpus)
    if num_tpus:
        res["TPU"] = float(num_tpus)
    return {k: v for k, v in res.items() if v}


def _prepare_env(worker, env: Optional[dict]) -> Optional[dict]:
    if not env or not (env.get("working_dir") or env.get("py_modules")
                       or env.get("pip") or env.get("conda")):
        return env
    from ray_tpu.core.runtime_env import prepare_runtime_env

    return prepare_runtime_env(worker, env)


def _placement_from_opts(opts) -> Optional[dict]:
    strategy = opts.get("scheduling_strategy")
    if strategy is None:
        return None
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return {
            "pg": strategy.placement_group.id.hex(),
            "bundle": strategy.placement_group_bundle_index,
        }
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return {"node_id": strategy.node_id, "soft": strategy.soft}
    return None


def deadline_from_opts(opts) -> Optional[float]:
    """``deadline_s`` (relative seconds) -> absolute wall-clock deadline;
    None when unset or the RAY_TPU_DEADLINES kill switch is off."""
    ds = opts.get("deadline_s")
    if ds is None or not config.deadlines:
        return None
    ds = float(ds)
    if ds < 0:
        raise ValueError("deadline_s must be >= 0")
    return time.time() + ds


class RemoteFunction:
    def __init__(self, function, **options):
        self._function = function
        self._options = options
        # Resources are a pure function of the (immutable) options:
        # build once and share the SAME dict across every spec this
        # function submits — nobody mutates spec.resources, and within
        # one dburst frame the pickler memoizes the shared dict so a
        # burst pays its serialization once instead of per call.
        self._resources = _build_resources(options)
        self.__name__ = getattr(function, "__name__", "remote_fn")
        self.__doc__ = getattr(function, "__doc__", None)

    def options(self, **new_options) -> "RemoteFunction":
        merged = copy.copy(self._options)
        merged.update(new_options)
        return RemoteFunction(self._function, **merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, opts):
        worker = global_worker()
        fid, blob = worker.register_function(self._function)
        out_args, out_kwargs, inner_refs = worker._prepare_args(args, kwargs)
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        if streaming:
            from ray_tpu.core.task_spec import STREAMING_RETURNS

            num_returns = STREAMING_RETURNS
        max_retries = (0 if streaming
                       else opts.get("max_retries", config.task_retry_default))
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            kind=NORMAL_TASK,
            name=opts.get("name") or self.__name__,
            function_blob=blob,
            function_id=fid,
            args=out_args,
            kwargs=out_kwargs,
            inner_refs=inner_refs or None,
            num_returns=num_returns,
            resources=(self._resources if opts is self._options
                       else _build_resources(opts)),
            max_retries=max_retries,
            retries_left=max_retries,
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            replicate=bool(opts.get("_replicate", False)),
            runtime_env=_prepare_env(worker, opts.get("runtime_env")),
            placement=_placement_from_opts(opts),
            deadline=deadline_from_opts(opts),
        )
        refs = submit_with_span(worker, spec)
        if streaming:
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id)
        if spec.num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node (reference: ``RemoteFunction.bind`` →
        `python/ray/dag/dag_node.py`); execute with ``node.execute()`` or
        run durably via ``ray_tpu.workflow.run``."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self.__name__}' cannot be called directly; "
            f"use '{self.__name__}.remote()'."
        )
