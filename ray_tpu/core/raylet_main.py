"""Standalone raylet (node) process (reference: `src/ray/raylet/main.cc:109`).

One process per node: owns the node's shm object store, worker pool, local
scheduler, and the TCP listener peers/drivers connect to.  Registers with
the GCS given by ``--gcs`` and heartbeats until terminated.

Prints ``RAYLET node_id=<hex> port=<port>`` on stdout once up.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import uuid


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs", required=True, help="GCS host:port")
    parser.add_argument("--ip", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="{}",
                        help='JSON, e.g. {"CPU": 4, "TPU": 1}')
    parser.add_argument("--session-dir", default=None)
    parser.add_argument("--store-mb", type=int, default=None)
    args = parser.parse_args()

    # Crash forensics: fatal-signal stack dumps on stderr for the node
    # daemon too (the raylet hosts no user code, but a native-codec or
    # shm-store segfault should leave a trace, not a silent exit).
    import faulthandler

    faulthandler.enable()

    from ray_tpu.core.config import config
    from ray_tpu.core.object_store import create_store_file
    from ray_tpu.core.raylet import Raylet

    resources = {k: float(v) for k, v in json.loads(args.resources).items()}
    resources.setdefault("CPU", float(os.cpu_count() or 1))

    session_dir = args.session_dir or os.path.join(
        config.temp_dir, f"node_{os.getpid()}_{uuid.uuid4().hex[:6]}")
    os.makedirs(session_dir, exist_ok=True)

    store_mb = args.store_mb or config.object_store_memory_mb
    shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else session_dir
    # Reclaim segments left by SIGKILLed/crashed raylets before adding
    # our own — otherwise every hard node kill leaks store_mb of shm
    # until reboot.
    from ray_tpu.core.object_store import sweep_dead_store_files

    sweep_dead_store_files(shm_dir)
    store_path = os.path.join(
        shm_dir, f"rt_store_{os.getpid()}_{uuid.uuid4().hex[:6]}")
    create_store_file(store_path, store_mb << 20)

    raylet = Raylet(
        session_dir, resources, store_path,
        worker_env={"RAY_TPU_SESSION_DIR": session_dir},
        gcs_address=args.gcs,
        node_ip=args.ip,
        listen_port=args.port,
    )
    print(f"RAYLET node_id={raylet.node_id} port={raylet.tcp_port}",
          flush=True)

    stop = threading.Event()
    raylet.on_fatal = stop.set  # GCS lost -> exit instead of lingering

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    raylet.shutdown()
    try:
        os.unlink(store_path)
    except OSError:
        pass
    import shutil

    shutil.rmtree(store_path + ".spill", ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
