"""Per-process worker state — the ``CoreWorker`` equivalent.

Reference analogue: `src/ray/core_worker/core_worker.h:284` +
`python/ray/_private/worker.py`.  One ``Worker`` per process:

  * DRIVER mode — owns the ``Raylet`` (in-process event thread), talks to it
    with direct closures; owns the session (store file, worker pool).
  * WORKER mode — subprocess connected to the raylet socket; executes tasks.
  * LOCAL mode — ``init(local_mode=True)``: tasks execute inline in the
    driver (reference: ``ray.init(local_mode=True)``), for debugging.

Result plane: values ≤ ``config.inline_object_max_bytes`` travel inline over
the control socket (reference inlines ≤100KB returns, `core_worker.h:988`);
larger values go through the shm object store with zero-copy reads.
"""

from __future__ import annotations

import hashlib
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.core.config import config
from ray_tpu.util.locks import make_lock, make_rlock
from ray_tpu.core.exceptions import GetTimeoutError, TaskError
from ray_tpu.core.ids import FunctionID, ObjectID, WorkerID, put_counter
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import (
    InProcObjectStore,
    ShmObjectStore,
    create_store_file,
)
from ray_tpu.core.raylet import Raylet
from ray_tpu.core.task_spec import TaskSpec

DRIVER = "driver"
WORKER = "worker"
LOCAL = "local"

_global_worker: Optional["Worker"] = None
_init_lock = make_lock("worker.init")

# ---------------------------------------------------------------------------
# Process-local reference counting (reference: ReferenceCounter,
# `src/ray/core_worker/reference_count.h:61`).  ObjectRef __init__/__del__
# call these; when this process's count for an object reaches zero the
# worker tells its raylet, which frees the object once nobody holds it.

_ref_counts: Dict["ObjectID", int] = {}  # guard: _ref_lock
# RLock: a GC pass triggered by an allocation INSIDE these functions can
# finalize an ObjectRef on the same thread, re-entering note_ref_dropped.
_ref_lock = make_rlock("worker.refcount")
_pending_events: List[tuple] = []  # guard: _ref_lock
# Batch threshold: freeing is latency-tolerant (a 0.5s raylet timer drains
# stragglers), so a bigger batch just means fewer raylet hops — at 8 a 10k
# fan-out cost ~2.5k event-loop posts; 64 cuts that 8x.
_REF_EVENT_BATCH = 64


def note_ref_created(oid):
    flush = None
    with _ref_lock:
        n = _ref_counts.get(oid, 0)
        _ref_counts[oid] = n + 1
        if n == 0:
            _pending_events.append(("h", oid))
            if len(_pending_events) >= _REF_EVENT_BATCH:
                flush = list(_pending_events)
                _pending_events.clear()
    if flush is not None:
        _flush_events(flush)


def note_ref_dropped(oid):
    flush = None
    with _ref_lock:
        n = _ref_counts.get(oid, 0) - 1
        if n > 0:
            _ref_counts[oid] = n
            return
        _ref_counts.pop(oid, None)
        _pending_events.append(("r", oid))
        if len(_pending_events) >= _REF_EVENT_BATCH:
            flush = list(_pending_events)
            _pending_events.clear()
    if flush is not None:
        _flush_events(flush)


def note_refs_created(oids):
    """Bulk pin: one lock round for a whole arg list (the direct burst
    path pins every inner ref of a submit under a single acquisition
    instead of one per oid)."""
    flush = None
    with _ref_lock:
        for oid in oids:
            n = _ref_counts.get(oid, 0)
            _ref_counts[oid] = n + 1
            if n == 0:
                _pending_events.append(("h", oid))
        if len(_pending_events) >= _REF_EVENT_BATCH:
            flush = list(_pending_events)
            _pending_events.clear()
    if flush is not None:
        _flush_events(flush)


def note_refs_dropped(oids):
    """Bulk release — the counterpart of :func:`note_refs_created`."""
    flush = None
    with _ref_lock:
        for oid in oids:
            n = _ref_counts.get(oid, 0) - 1
            if n > 0:
                _ref_counts[oid] = n
                continue
            _ref_counts.pop(oid, None)
            _pending_events.append(("r", oid))
        if len(_pending_events) >= _REF_EVENT_BATCH:
            flush = list(_pending_events)
            _pending_events.clear()
    if flush is not None:
        _flush_events(flush)


def flush_pending_releases():
    with _ref_lock:
        flush = list(_pending_events)
        _pending_events.clear()
    if flush:
        _flush_events(flush)


def _flush_events(events):
    w = _global_worker
    if w is None:
        return
    try:
        w.send_ref_events(events)
    except Exception:  # noqa: BLE001 shutdown races
        pass


def global_worker() -> "Worker":
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_worker


def is_initialized() -> bool:
    return _global_worker is not None


class Worker:
    def __init__(self, mode: str):
        self.mode = mode
        self.worker_id = WorkerID.from_random()
        self.store = None
        self.raylet: Optional[Raylet] = None
        self.session_dir: Optional[str] = None
        self._pushed_functions: set = set()
        # id(fn) -> (fn, fid, blob); bounded LRU — a driver minting fresh
        # closures in a loop must not pin them (and their captured data)
        # forever.
        from collections import OrderedDict as _OD

        self._fn_memo: "Dict[int, tuple]" = _OD()
        self._fn_cache: Dict[bytes, Any] = {}
        self.actor_instance = None  # worker mode: the hosted actor
        self.current_actor_id = None
        self.namespace = ""
        # Direct worker→worker transport (core/direct.py): caller-side
        # channel manager, wired by DriverWorker / worker_main / client —
        # None when direct calls are disabled (or in local mode).
        self._direct = None

    # ------------------------------------------------------------ serialization

    def _serialize_value(self, value) -> serialization.SerializedObject:
        return serialization.serialize(value)

    def _prepare_args(self, args: Sequence, kwargs: Dict):
        """Top-level ObjectRef args become dependencies; plain values are
        serialized inline, or promoted to the store when large (reference:
        LocalDependencyResolver inlines small args,
        `transport/dependency_resolver.cc`).  Returns (args, kwargs,
        inner_refs) — inner_refs are ObjectIDs of refs serialized INSIDE
        inline values; the spec pins them until the task completes."""
        inner: list = []
        out_args = []
        for a in args:
            out_args.append(self._prepare_arg(a, inner))
        out_kwargs = [(k, self._prepare_arg(v, inner))
                      for k, v in kwargs.items()]
        return out_args, out_kwargs, inner

    def _prepare_arg(self, value, inner: list):
        if isinstance(value, ObjectRef):
            return ("ref", value.id())
        ser, refs = serialization.serialize_with_refs(value)
        blob = ser.to_bytes()
        if len(blob) > config.inline_object_max_bytes:
            ref = self.put(value)  # put() re-collects and pins via contains
            return ("ref", ref.id())
        inner.extend(refs)
        return ("v", blob)

    def register_function(self, callable_obj) -> Tuple[FunctionID, Optional[bytes]]:
        """Returns (function_id, inline_blob_or_None); large callables are
        pushed to the GCS function table once (reference function_manager).

        Per-object memo: re-pickling the same function on EVERY .remote()
        was ~13% of async submission cost (profiled); identity-keyed, with
        a mutation fingerprint holding STRONG REFS to the attribute dict's
        values, __defaults__ and __code__ and comparing by identity — so
        rebinding a function attribute or its defaults re-pickles instead
        of silently shipping the old state (and the kept refs make the
        `is` checks immune to id reuse).  In-place mutation of a captured
        object's internals remains export-once, matching the reference's
        function manager semantics."""
        memo = self._fn_memo.get(id(callable_obj))
        if memo is not None and memo[0] is callable_obj:
            # memo-hit fast path: fingerprint against the LIVE attribute
            # dict without snapshotting it — the copy below only happens
            # on miss/re-pickle (the hit path runs once per .remote()
            # and the per-call dict copy was ~5% of burst submit cost)
            sd, sdef, scode = memo[3]
            cur = getattr(callable_obj, "__dict__", None) or {}
            if (getattr(callable_obj, "__defaults__", None) is sdef
                    and getattr(callable_obj, "__code__", None) is scode
                    and cur.keys() == sd.keys()
                    and all(sd[k] is cur[k] for k in sd)):
                self._fn_memo.move_to_end(id(callable_obj))
                return memo[1], memo[2]
        fp = (dict(getattr(callable_obj, "__dict__", None) or {}),
              getattr(callable_obj, "__defaults__", None),
              getattr(callable_obj, "__code__", None))
        blob = cloudpickle.dumps(callable_obj)
        fid = FunctionID(hashlib.sha1(blob).digest()[:16])
        if len(blob) <= config.inline_object_max_bytes:
            out = (fid, blob)
        else:
            if fid not in self._pushed_functions:
                self._push_function(fid, blob)
                self._pushed_functions.add(fid)
            out = (fid, None)
        # keep a strong ref to the callable so id() stays unambiguous
        self._fn_memo[id(callable_obj)] = (callable_obj, out[0], out[1], fp)
        while len(self._fn_memo) > 256:
            self._fn_memo.popitem(last=False)
        return out

    def _push_function(self, fid: FunctionID, blob: bytes):
        if self.mode == DRIVER:
            self.raylet.gcs.put_function(fid.binary(), blob)
        else:
            self._request("put_function", id=fid.binary(), blob=blob)

    # ------------------------------------------------------------ core ops

    def submit_spec(self, spec: TaskSpec) -> List[ObjectRef]:
        self._stamp_lineage(spec)
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        d = self._direct
        if d is not None and d.try_submit(spec):
            return refs  # rode the direct channel (or its fallback)
        self._submit_relayed(spec)
        return refs

    def _stamp_lineage(self, spec: TaskSpec):
        """Deadline propagation + cancel fan-out edges: a spec submitted
        FROM a running task inherits the tightest enclosing deadline and
        records its parent task id, so deadline expiry / recursive cancel
        reach nested work wherever it was spawned.  Actor CREATION never
        inherits a deadline — the actor outlives the request that made it
        (the raylet's admission path exempts creations for the same
        reason; inheriting here would have the worker kill a creation the
        raylet deliberately admitted)."""
        from ray_tpu.core.task_spec import ACTOR_CREATION_TASK
        from ray_tpu.runtime_context import _current_deadline, _current_task_id

        parent = _current_task_id.get()
        if parent is not None and spec.parent_task_id is None:
            spec.parent_task_id = parent
        if not config.deadlines or spec.kind == ACTOR_CREATION_TASK:
            return
        ambient = _current_deadline.get()
        if ambient is not None and (spec.deadline is None
                                    or ambient < spec.deadline):
            spec.deadline = ambient

    def _submit_relayed(self, spec: TaskSpec):
        """The raylet-mediated submit path — also the direct transport's
        fallback/reconcile target (must not re-enter try_submit)."""
        if self.mode == DRIVER:
            self.raylet.call_async(self.raylet.submit_task, spec)
        else:
            self._send({"t": "submit", "spec": spec})

    def send_ref_events(self, events: List[tuple]):
        """Ordered hold/release transitions for this process's ObjectRefs."""
        if self.mode == DRIVER:
            self.raylet.call_async(self.raylet.apply_ref_events, events)
        elif self.mode == LOCAL:
            for kind, oid in events:
                if kind == "r":
                    self._objects.pop(oid, None)
        else:
            try:
                self._send({"t": "ref_events", "events": events})
            except Exception:  # noqa: BLE001 socket teardown
                pass

    def put(self, value, _replicate: bool = False) -> ObjectRef:
        """``_replicate=True``: eagerly push a secondary copy to another
        node regardless of the RAY_TPU_REPLICATION_MIN_BYTES threshold
        (flagged puts route through the store even when small — an inline
        value lives only in its raylet's memory and cannot be served to a
        replica holder)."""
        flush_pending_releases()  # free before allocating under pressure
        oid = put_counter.next_object_id()
        ser, inner = serialization.serialize_with_refs(value)
        size = ser.total_bytes()
        inline = (size <= config.inline_object_max_bytes
                  and not (_replicate and self.store is not None))
        if inline or self.store is None:
            blob = ser.to_bytes()
            if self.mode == DRIVER:
                self.raylet.call_async(self.raylet._object_inline, oid, blob,
                                       inner)
            else:
                self._request("put_inline", id=oid.hex(), blob=blob,
                              contains=inner)
        else:
            self.store.put_serialized(oid, ser)
            if self.mode == DRIVER:
                def _mark(o=oid, n=size, inner=inner, rep=_replicate):
                    self.raylet._obj(o).size = n
                    self.raylet._object_in_store(o, contains=inner)
                    self.raylet._maybe_replicate(o, force=rep)
                self.raylet.call_async(_mark)
            else:
                self._request("register_stored", id=oid.hex(), size=size,
                              contains=inner, replicate=_replicate)
        return ObjectRef(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None):
        from ray_tpu.util import tracing as _tracing

        ids = [r.id() for r in refs]
        if _tracing.tracing_enabled():
            # caller-wakeup hop: the get() that consumes a traced submit's
            # result closes the request loop (ctx recorded at submit time,
            # consumed on first lookup)
            ctx = _tracing.lookup_get_ctx(ids)
            if ctx is not None:
                # a raised error marks the span ERROR in span.__exit__
                with _tracing.span("task.get", parent=ctx, n=len(ids)):
                    return self._get_inner(ids, timeout)
        return self._get_inner(ids, timeout)

    def _get_inner(self, ids, timeout: Optional[float] = None):
        fast: Dict[ObjectID, tuple] = {}
        d = self._direct
        deadline = None
        if d is not None:
            # Direct-call results resolve here first: in-flight calls are
            # waited on locally (the callee pushes straight back — no
            # raylet round trip), cached inline results decode in place,
            # and store-sized results fall through to the shm fast path.
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            for oid in ids:
                if oid in fast:
                    continue
                r = d.resolve(oid, deadline)
                if r is None:
                    continue
                if r[0] == "inline":
                    fast[oid] = (serialization.loads(r[1]),)
                elif r[0] == "error":
                    raise r[1]
                # ("store",): read via the store/raylet paths below
            if timeout is not None:
                timeout = max(0.0, deadline - time.monotonic())
        if self.mode in (DRIVER, WORKER) and self.store is not None:
            # Fast path: an object already SEALED in the local store needs
            # no raylet round trip (sealed implies the producing task
            # completed, and the caller's ref pins it against free) — read
            # it straight off the shm arena.  For the driver this skips two
            # thread hops + a wake syscall per get; for workers a full
            # socket round trip.  Misses (inline results, pending or
            # errored tasks, evicted/spilled objects) take the slow path,
            # which also owns reconstruction.
            miss: List[ObjectID] = []
            for oid in ids:
                if oid in fast:
                    continue
                if self.store.contains(oid):
                    try:
                        fast[oid] = (self.read_store_object(
                            oid,
                            timeout=60.0 if timeout is None else timeout),)
                        continue
                    except Exception:  # noqa: BLE001 evicted/raced: slow path
                        pass
                miss.append(oid)
            if not miss:
                if d is not None:
                    d.note_observed(ids)
                return [fast[oid][0] for oid in ids]
            return self._get_via_raylet(ids, miss, fast, timeout)
        return self._get_via_raylet(ids, [o for o in ids if o not in fast],
                                    fast, timeout)

    def _get_via_raylet(self, ids, fetch_ids, fast, timeout):
        """Resolve ``fetch_ids`` through the raylet, then assemble results
        for ``ids`` in order (``fast`` holds store-read values keyed by
        ObjectID, each wrapped in a 1-tuple)."""
        if self.mode == DRIVER:
            from ray_tpu.core.raylet import SimpleFuture

            fut = SimpleFuture()
            cancel_fut = self.raylet.call(self.raylet.async_get, fetch_ids,
                                          fut.set)
            try:
                results = fut.result(timeout)
            except TimeoutError:
                # Deregister the waiters we left behind in the raylet.
                def _cancel():
                    try:
                        cancel = cancel_fut.result(0)
                    except Exception:  # noqa: BLE001
                        return
                    if cancel is not None:
                        cancel()
                self.raylet.call_async(_cancel)
                raise GetTimeoutError(
                    f"get() timed out after {timeout}s"
                ) from None
        else:
            try:
                results = self._request(
                    "get", ids=[i.hex() for i in fetch_ids],
                    _wait_timeout=timeout
                )
            except TimeoutError:
                raise GetTimeoutError(
                    f"get() timed out after {timeout}s"
                ) from None
        if self._direct is not None:
            # every fetched id is now resolved — the delivery watermark
            # the direct transport's order-safe engagement waits on
            # (errored results don't count: a raylet-side failure proves
            # nothing about delivery of the calls before it)
            self._direct.note_observed(
                ids, errored={h for h, r in results.items()
                              if r[0] == "error"})
        out = []
        for oid in ids:
            hit = fast.get(oid)
            if hit is not None:
                out.append(hit[0])
                continue
            kind, *rest = results[oid.hex()]
            if kind == "error":
                raise rest[0]
            if kind == "inline":
                out.append(serialization.loads(rest[0]))
            else:  # store
                out.append(self.read_store_object(
                    oid, timeout=60.0 if timeout is None else timeout))
        return out

    def read_store_object(self, oid, attempts: int = 3,
                          timeout: Optional[float] = 60.0):
        """Store read with transparent lineage recovery: an LRU-evicted
        object is reconstructed by re-running its creating task
        (reference: `object_recovery_manager.h:41`).  ``timeout`` bounds
        each reseal wait (the re-executed task could hang)."""
        from ray_tpu.core.exceptions import GetTimeoutError, ObjectLostError

        for attempt in range(attempts):
            try:
                return self.store.get(oid)
            except ObjectLostError:
                if attempt == attempts - 1 or not self.reconstruct(oid):
                    raise
                # block until resealed (or inline/error this time around)
                try:
                    result = self._blocking_get_status([oid],
                                                       timeout)[oid.hex()]
                except TimeoutError:
                    raise GetTimeoutError(
                        f"reconstruction of {oid.hex()} timed out after "
                        f"{timeout}s") from None
                if result[0] == "inline":
                    return serialization.loads(result[1])
                if result[0] == "error":
                    raise result[1]

    def _blocking_get_status(self, oids, timeout: Optional[float] = None):
        if self.mode == DRIVER:
            from ray_tpu.core.raylet import SimpleFuture

            fut = SimpleFuture()
            self.raylet.call(self.raylet.async_get, oids, fut.set)
            return fut.result(timeout)
        return self._request("get", ids=[o.hex() for o in oids],
                             _wait_timeout=timeout)

    def reconstruct(self, oid) -> bool:
        if self.mode == DRIVER:
            return bool(self.raylet.call(
                self.raylet.reconstruct_object, oid).result())
        if self.mode == LOCAL:
            return False
        return bool(self._request("reconstruct", id=oid.hex()))

    def wait(self, refs: Sequence[ObjectRef], num_returns=1,
             timeout: Optional[float] = None):
        ids = [r.id() for r in refs]
        if self.mode == DRIVER:
            from ray_tpu.core.raylet import SimpleFuture

            fut = SimpleFuture()
            self.raylet.call_async(
                self.raylet.async_wait, ids, num_returns, timeout, fut.set
            )
            rep = fut.result()
        else:
            rep = self._request(
                "wait", ids=[i.hex() for i in ids],
                num_returns=num_returns, timeout=timeout,
            )
        ready_set = set(rep["ready"])
        ready = [r for r in refs if r.hex() in ready_set]
        not_ready = [r for r in refs if r.hex() not in ready_set]
        if self._direct is not None and ready:
            # errored refs count as ready but must NOT clear the direct
            # engagement watermark (see async_wait's reply_value)
            self._direct.note_observed(
                [r.id() for r in ready],
                errored=set(rep.get("errored") or ()))
        return ready, not_ready

    def free(self, refs: Sequence[ObjectRef]):
        hexes = [r.hex() for r in refs]
        if self.mode == DRIVER:
            def _free():
                for h in hexes:
                    self.raylet.drop_object(ObjectID.from_hex(h))
            self.raylet.call_async(_free)
        else:
            self._request("free", ids=hexes)
        if self.store is not None:
            for r in refs:
                try:
                    self.store.delete(r.id())
                except Exception:  # noqa: BLE001
                    pass

    # KV (GCS KV — backs runtime envs, Train/Tune metadata, Serve).  The
    # driver holds the GCS handle directly (embedded GcsCore or GcsClient);
    # workers go through their raylet which proxies to the GCS.
    def kv_put(self, key: bytes, value: bytes, namespace: str = ""):
        if self.mode == DRIVER:
            self.raylet.gcs.kv_put(namespace, key, value)
        else:
            self._request("kv_put", ns=namespace, key=key, val=value)

    def kv_get(self, key: bytes, namespace: str = "") -> Optional[bytes]:
        if self.mode == DRIVER:
            return self.raylet.gcs.kv_get(namespace, key)
        return self._request("kv_get", ns=namespace, key=key)

    def kv_del(self, key: bytes, namespace: str = ""):
        if self.mode == DRIVER:
            return self.raylet.gcs.kv_del(namespace, key)
        return self._request("kv_del", ns=namespace, key=key)

    def kv_keys(self, prefix: bytes, namespace: str = "") -> List[bytes]:
        if self.mode == DRIVER:
            return self.raylet.gcs.kv_keys(namespace, prefix)
        return self._request("kv_keys", ns=namespace, prefix=prefix)

    def stream_next(self, task_id, index: int,
                    timeout: Optional[float] = None) -> dict:
        """Block until item ``index`` of a streaming task exists (or the
        stream ended/errored).  Returns {"kind": "item"|"end"|"error",...}."""
        if self.mode == DRIVER:
            from ray_tpu.core.raylet import SimpleFuture

            fut = SimpleFuture()
            cancel_fut = self.raylet.call(
                self.raylet.async_stream_next, task_id, index, fut.set)
            try:
                return fut.result(timeout)
            except TimeoutError:
                def _cancel():
                    try:
                        cancel = cancel_fut.result(0)
                    except Exception:  # noqa: BLE001
                        return
                    if cancel is not None:
                        cancel()
                self.raylet.call_async(_cancel)
                raise
        return self._request("stream_next", task_id=task_id, index=index,
                             _wait_timeout=timeout)

    def cancel(self, ref, force: bool = False, recursive: bool = True) -> bool:
        if self.mode == LOCAL:
            return False
        hit = False
        if self._direct is not None:
            # the call may be in flight on a direct channel the raylet
            # never saw dispatch: the cancel frame must reach the dialed
            # callee's in-flight registry, not just the raylet queues
            hit = self._direct.cancel(ref.id())
        if self.mode == DRIVER:
            return bool(self.raylet.call(
                self.raylet.cancel_task, ref.id(), force,
                recursive).result()) or hit
        return bool(self._request("cancel_task", id=ref.hex(), force=force,
                                  recursive=recursive)) or hit

    def gcs_nodes(self) -> List[dict]:
        if self.mode == DRIVER:
            return self.raylet.gcs.nodes()
        if self.mode == LOCAL:
            return []
        return self._request("nodes")

    # ------------------------------------------------------------ worker mode

    def _send(self, msg):
        raise NotImplementedError

    def _request(self, op, **fields):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Driver bring-up / teardown


def _gc_stale_stores(shm_dir: str):
    """Remove store files whose owning driver (pid in the name) is gone —
    crash-safety for the file-backed shm arena."""
    try:
        for name in os.listdir(shm_dir):
            if not name.startswith("rt_store_"):
                continue
            parts = name.split("_")
            try:
                pid = int(parts[2])
            except (IndexError, ValueError):
                continue
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                try:
                    os.unlink(os.path.join(shm_dir, name))
                except OSError:
                    pass
                import shutil

                shutil.rmtree(os.path.join(shm_dir, name + ".spill"),
                              ignore_errors=True)
            except PermissionError:
                pass
    except OSError:
        pass


class DriverWorker(Worker):
    def __init__(self, num_cpus=None, num_tpus=None, resources=None,
                 object_store_memory=None, namespace: str = ""):
        super().__init__(DRIVER)
        self.namespace = namespace or ""
        ts = time.strftime("%Y%m%d-%H%M%S")
        self.session_dir = os.path.join(
            config.temp_dir, f"session_{ts}_{os.getpid()}_{uuid.uuid4().hex[:6]}"
        )
        os.makedirs(self.session_dir, exist_ok=True)

        total = {"CPU": float(num_cpus if num_cpus is not None else os.cpu_count())}
        if num_tpus is None:
            num_tpus = config.num_chips
            if num_tpus == 0 and "jax" in __import__("sys").modules:
                try:
                    import jax

                    num_tpus = sum(
                        1 for d in jax.devices() if d.platform != "cpu"
                    )
                except Exception:  # noqa: BLE001
                    num_tpus = 0
        if num_tpus:
            total["TPU"] = float(num_tpus)
        total.update(resources or {})

        store_mb = (object_store_memory or config.object_store_memory_mb * (1 << 20)) // (1 << 20)
        store_path = None
        if not config.object_store_fallback_inproc:
            shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else self.session_dir
            _gc_stale_stores(shm_dir)
            store_path = os.path.join(
                shm_dir, f"rt_store_{os.getpid()}_{uuid.uuid4().hex[:6]}"
            )
            create_store_file(store_path, int(store_mb) << 20)
            self.store = ShmObjectStore(store_path)
        else:
            self.store = InProcObjectStore()

        self.store_path = store_path
        self.raylet = Raylet(
            self.session_dir, total, store_path,
            worker_env={"RAY_TPU_SESSION_DIR": self.session_dir},
        )
        if config.prestart_workers:
            n = min(int(total["CPU"]), 4)
            for _ in range(n):
                self.raylet.call_async(self.raylet._spawn_worker, "cpu")

        # Periodic ref-event flush: the batching threshold (8) can leave a
        # tail of release events unsent forever on an idle driver, pinning
        # their objects; a 0.5s raylet timer drains them.
        def _ref_flush_tick():
            flush_pending_releases()
            self.raylet.add_timer(0.5, _ref_flush_tick)

        self.raylet.call_async(
            lambda: self.raylet.add_timer(0.5, _ref_flush_tick))
        # Direct worker→worker transport (caller side): actor calls and
        # lease-reused tasks dial the callee worker directly after the
        # raylet brokers the address; raylet path kept for first-call,
        # recovery, and fenced peers.
        if config.direct_calls:
            from ray_tpu.core.direct import DirectCallClient

            raylet = self.raylet
            self._direct = DirectCallClient(
                self,
                broker=lambda aid: raylet.call(
                    raylet.direct_call_info, aid).result(2.0),
                resubmit=self._submit_relayed,
                lease=lambda spec: raylet.call(
                    raylet.acquire_direct_lease, spec).result(2.0),
                lease_release=lambda lid: raylet.call_async(
                    raylet.release_direct_lease, lid),
            )
            # actor-death / node-SUSPECT fences reach this in-process
            # caller by direct callback (workers get control frames)
            raylet.direct_fence_cb = self._direct.on_fence
        # Clean up the shm store even if the user forgets shutdown() or the
        # driver exits on an exception.
        import atexit

        atexit.register(self._atexit_cleanup)

    def _atexit_cleanup(self):
        try:
            self.shutdown()
        except Exception:  # noqa: BLE001
            pass

    def shutdown(self):
        if self._direct is not None:
            self._direct.close()  # releases leases before the pool dies
            self._direct = None
        self.raylet.shutdown()
        try:
            self.store.close()
        except Exception:  # noqa: BLE001
            pass
        if self.store_path and os.path.exists(self.store_path):
            try:
                os.unlink(self.store_path)
            except OSError:
                pass
        if self.store_path:
            import shutil

            shutil.rmtree(self.store_path + ".spill", ignore_errors=True)


# ---------------------------------------------------------------------------
# Local mode: inline execution (ray.init(local_mode=True) equivalent)


class LocalWorker(Worker):
    def __init__(self):
        super().__init__(LOCAL)
        self._objects: Dict[ObjectID, Tuple[str, Any]] = {}
        self._actors: Dict[Any, Any] = {}
        self._local_streams: Dict[Any, int] = {}
        self.store = InProcObjectStore()

    def stream_next(self, task_id, index, timeout=None):
        total = self._local_streams.get(task_id)
        if total is None:
            return {"kind": "error",
                    "error": ValueError(f"unknown stream {task_id.hex()}")}
        return {"kind": "item"} if index < total else {"kind": "end"}

    def submit_spec(self, spec: TaskSpec) -> List[ObjectRef]:
        from ray_tpu.core.task_spec import (
            ACTOR_CREATION_TASK,
            ACTOR_TASK,
            STREAMING_RETURNS,
        )

        fn = (cloudpickle.loads(spec.function_blob)
              if spec.function_blob is not None else None)
        args, kwargs = self._resolve_args(spec)
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        try:
            if spec.kind == ACTOR_CREATION_TASK:
                inst = fn(*args, **kwargs)
                self._actors[spec.actor_id] = inst
                result = None
            elif spec.kind == ACTOR_TASK:
                inst = self._actors[spec.actor_id]
                result = getattr(inst, spec.method_name)(*args, **kwargs)
            else:
                result = fn(*args, **kwargs)
            if spec.num_returns == STREAMING_RETURNS:
                items = list(result)  # local mode: drain eagerly
                for i, v in enumerate(items):
                    self._objects[spec.stream_item_id(i)] = ("v", v)
                self._local_streams[spec.task_id] = len(items)
                self._objects[refs[0].id()] = ("v", len(items))
            elif spec.num_returns == 1:
                self._objects[refs[0].id()] = ("v", result)
            else:
                for r, v in zip(refs, result):
                    self._objects[r.id()] = ("v", v)
        except Exception as e:  # noqa: BLE001
            import traceback

            err = TaskError(spec.name, traceback.format_exc(), e)
            for r in refs:
                self._objects[r.id()] = ("e", err)
        return refs

    def _resolve_args(self, spec):
        def resolve(entry):
            kind, payload = entry
            if kind == "ref":
                tag, v = self._objects[payload]
                if tag == "e":
                    raise v
                return v
            return serialization.loads(payload)

        args = [resolve(a) for a in spec.args]
        kwargs = {k: resolve(v) for k, v in spec.kwargs}
        return args, kwargs

    def put(self, value, _replicate: bool = False) -> ObjectRef:
        oid = put_counter.next_object_id()
        self._objects[oid] = ("v", value)
        return ObjectRef(oid)

    def get(self, refs, timeout=None):
        out = []
        for r in refs:
            tag, v = self._objects[r.id()]
            if tag == "e":
                raise v
            out.append(v)
        return out

    def wait(self, refs, num_returns=1, timeout=None):
        return list(refs[:num_returns]), list(refs[num_returns:])

    def free(self, refs):
        for r in refs:
            self._objects.pop(r.id(), None)

    def kv_put(self, key, value, namespace=""):
        self._objects[("kv", namespace, key)] = ("v", value)

    def kv_get(self, key, namespace=""):
        entry = self._objects.get(("kv", namespace, key))
        return entry[1] if entry else None

    def kv_del(self, key, namespace=""):
        return self._objects.pop(("kv", namespace, key), None) is not None

    def kv_keys(self, prefix, namespace=""):
        return [k[2] for k in self._objects
                if isinstance(k, tuple) and k[0] == "kv" and k[1] == namespace
                and k[2].startswith(prefix)]

    def shutdown(self):
        self._objects.clear()
        self._actors.clear()


# ---------------------------------------------------------------------------


def init_worker(worker: Worker):
    global _global_worker
    with _init_lock:
        _global_worker = worker


def clear_worker():
    global _global_worker
    with _init_lock:
        _global_worker = None
