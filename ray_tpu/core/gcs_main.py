"""Standalone GCS server process (reference:
`src/ray/gcs/gcs_server/gcs_server_main.cc`).

Prints ``GCS_ADDRESS host:port`` on stdout once listening so launchers
(`ray_tpu/cluster_utils.py`, the CLI) can read the bound ephemeral port.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--persist", default=None,
                        help="snapshot file for GCS fault tolerance "
                        "(reference: Redis-backed GCS persistence)")
    args = parser.parse_args()

    # Crash forensics: fatal-signal stack dumps for the control plane.
    import faulthandler

    faulthandler.enable()

    from ray_tpu.core.gcs import GcsServer
    from ray_tpu.util import profiling

    server = GcsServer(host=args.host, port=args.port,
                       persist_path=args.persist)
    print(f"GCS_ADDRESS {server.address}", flush=True)

    # Continuous profiling of the GCS process itself (where do control-
    # plane microseconds go?): samples flush straight into the local
    # profile table under the reserved "gcs" producer key — no raylet in
    # this process to relay through.
    profiling.ensure_profiler("gcs")
    profiling.set_flush_target(
        lambda samples, dropped: server.core.add_profile_samples(
            "gcs", samples, dropped))

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
