"""Standalone GCS server process (reference:
`src/ray/gcs/gcs_server/gcs_server_main.cc`).

Prints ``GCS_ADDRESS host:port`` on stdout once listening so launchers
(`ray_tpu/cluster_utils.py`, the CLI) can read the bound ephemeral port.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--persist", default=None,
                        help="snapshot file for GCS fault tolerance "
                        "(reference: Redis-backed GCS persistence)")
    args = parser.parse_args()

    from ray_tpu.core.gcs import GcsServer

    server = GcsServer(host=args.host, port=args.port,
                       persist_path=args.persist)
    print(f"GCS_ADDRESS {server.address}", flush=True)

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
