"""Python client for the native shared-memory object store.

Counterpart of the reference's plasma client
(`src/ray/object_manager/plasma/client.h:146`) — but with no store server
process: all metadata lives in the shm mapping itself (see
`ray_tpu/native/src/object_store.cc` for the design rationale), so create /
seal / get are lock-protected shm operations, not socket round trips.

Zero-copy: ``get`` deserializes with out-of-band buffers that alias the mmap
directly; the store pin is released when the returned root object is
garbage-collected (weakref.finalize).  Known round-1 limitation: if a caller
extracts a numpy view from the returned object and drops the root, the pin is
released early and the buffer becomes evictable under memory pressure (the
mapping itself stays valid, so this can never segfault).
"""

from __future__ import annotations

import ctypes
import itertools
import mmap as _mmap
import os
import threading
import time
import weakref
from typing import Any, Optional

from ray_tpu.core import serialization
from ray_tpu.core.config import config
from ray_tpu.core.ids import ObjectID
from ray_tpu.util.locks import make_lock


from ray_tpu.core.exceptions import ObjectLostError as _BaseObjectLostError

config.define("object_store_spill", bool, True,
              "Overflowing puts spill to disk (reference: "
              "local_object_manager.h:41) instead of LRU-evicting sealed "
              "objects; False restores pure in-memory LRU behavior.")


class ObjectStoreFullError(RuntimeError):
    pass


# Disambiguates concurrent spill tmp files within one process (itertools
# .count() is GIL-atomic).
_spill_tmp_seq = itertools.count()


class ObjectLostError(_BaseObjectLostError):
    """Canonical ray_tpu ObjectLostError, enriched with the object id (so
    ``except ray_tpu.ObjectLostError`` catches store-level evictions)."""

    def __init__(self, object_id: ObjectID):
        super().__init__(
            f"Object {object_id.hex()} was evicted or never created."
        )
        self.object_id = object_id


class _StoreStats(ctypes.Structure):
    _fields_ = [
        ("capacity", ctypes.c_uint64),
        ("bytes_in_use", ctypes.c_uint64),
        ("num_objects", ctypes.c_uint64),
        ("num_evictions", ctypes.c_uint64),
    ]


def _load_lib():
    from ray_tpu.native.build import lib_path

    lib = ctypes.CDLL(lib_path())
    lib.rt_store_init.restype = ctypes.c_int
    lib.rt_store_init.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.rt_store_attach.restype = ctypes.c_void_p
    lib.rt_store_attach.argtypes = [ctypes.c_char_p]
    lib.rt_store_detach.argtypes = [ctypes.c_void_p]
    lib.rt_create.restype = ctypes.c_int
    lib.rt_create.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rt_create_opts.restype = ctypes.c_int
    lib.rt_create_opts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.rt_seal.restype = ctypes.c_int
    lib.rt_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_get.restype = ctypes.c_int
    lib.rt_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rt_release.restype = ctypes.c_int
    lib.rt_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_contains.restype = ctypes.c_int
    lib.rt_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_delete.restype = ctypes.c_int
    lib.rt_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_abort.restype = ctypes.c_int
    lib.rt_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(_StoreStats)]
    return lib


_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


_MADV_POPULATE_WRITE = 23  # linux 5.14+; not in the mmap module yet


def _prefault(path: str):
    """Materialize the arena's tmpfs pages up front (MADV_POPULATE_WRITE
    keeps contents intact, so it is safe to run concurrently with puts).
    Skipping this leaves first-touch page-fault zeroing on the put hot
    path — measured 1.8 GiB/s vs 5.3 GiB/s after prefault."""
    try:
        fd = os.open(path, os.O_RDWR)
        try:
            m = _mmap.mmap(fd, os.fstat(fd).st_size)
        finally:
            os.close(fd)
        try:
            m.madvise(_MADV_POPULATE_WRITE)
        finally:
            m.close()
    except (OSError, ValueError):
        pass  # old kernel / permissions: stay lazy


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def sweep_dead_store_files(shm_dir: str = "/dev/shm") -> list:
    """Reclaim store segments abandoned by crashed raylets.

    A segment's name embeds its creating raylet's pid
    (``rt_store_<pid>_<hex>``, `raylet_main.py`); the raylet unlinks it
    on clean shutdown, but SIGKILL / OOM-kill / a segfault skips that —
    and a shm file nobody will ever unlink eats host memory forever.
    Every raylet sweeps at startup: any segment whose creator pid is
    gone is garbage by construction (live raylets' pids still exist, so
    their segments are never touched).  Returns the removed paths."""
    import shutil

    removed = []
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return removed
    for name in names:
        if not name.startswith("rt_store_") or name.endswith(".spill"):
            continue
        parts = name.split("_")
        try:
            pid = int(parts[2])
        except (IndexError, ValueError):
            continue
        if _pid_alive(pid):
            continue
        path = os.path.join(shm_dir, name)
        try:
            os.unlink(path)
        except OSError:
            continue
        shutil.rmtree(path + ".spill", ignore_errors=True)
        removed.append(path)
    return removed


def create_store_file(path: str, capacity_bytes: int, table_cap: int = 1 << 16):
    rc = _get_lib().rt_store_init(path.encode(), capacity_bytes, table_cap)
    if rc != 0:
        raise OSError(-rc, f"rt_store_init({path}) failed")
    # Background: ~0.5 ms/MB; don't block init on it.
    import threading

    threading.Thread(target=_prefault, args=(path,), daemon=True,
                     name="store-prefault").start()


class ShmObjectStore:
    """A client connection (attach) to a shm store file.

    Overflow spilling (reference: `src/ray/raylet/local_object_manager.h:41`
    ``SpillObjectUptoMaxThroughput``): when the arena cannot fit a new
    object, its bytes go to a per-store spill DIRECTORY on disk and reads
    restore them transparently (mmap + zero-copy deserialize).  The
    serverless-store design moves spilling into the writing client — no
    IO-worker processes — with the spill dir shared by every client of
    the store file."""

    def __init__(self, path: str, spill_dir: Optional[str] = None):
        self._path = path
        self._lib = _get_lib()
        # Serializes close() against native calls from data-plane threads
        # (serve/receive): a check-then-act on _handle alone could pass a
        # NULL/freed handle into C during raylet shutdown.
        self._close_lock = make_lock("object_store.close")
        self._handle = self._lib.rt_store_attach(path.encode())  # guard: _close_lock
        if not self._handle:
            raise OSError(f"cannot attach to object store at {path}")
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            self._mmap = _mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._view = memoryview(self._mmap)
        self._spill_dir = spill_dir or (path + ".spill")

    # -- spill plane ----------------------------------------------------------

    def _spill_path(self, object_id: ObjectID) -> str:
        return os.path.join(self._spill_dir, object_id.hex())

    def spill(self, object_id: ObjectID, ser: "serialization.SerializedObject"):
        """Write a serialized object to the spill dir (atomic rename)."""
        buf = bytearray(ser.total_bytes())
        ser.write_into(memoryview(buf))
        self.spill_raw(object_id, buf)

    def spill_raw(self, object_id: ObjectID, data):
        os.makedirs(self._spill_dir, exist_ok=True)
        # Per-process counter in the tmp name: a pid-only suffix collides
        # when two THREADS of one process spill the same object
        # concurrently (one writer truncates the file under the other).
        tmp = (self._spill_path(object_id)
               + f".tmp{os.getpid()}.{next(_spill_tmp_seq)}")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._spill_path(object_id))

    def has_spilled(self, object_id: ObjectID) -> bool:
        return os.path.exists(self._spill_path(object_id))

    def read_spilled(self, object_id: ObjectID):
        """Deserialize straight off a file mapping (buffers alias the map;
        the finalizer keeps it alive like the shm path does)."""
        import weakref

        try:
            fd = os.open(self._spill_path(object_id), os.O_RDONLY)
        except OSError:
            # raced a free()/delete() between has_spilled and open
            raise ObjectLostError(object_id) from None
        try:
            m = _mmap.mmap(fd, 0, prot=_mmap.PROT_READ)
        finally:
            os.close(fd)
        value = serialization.deserialize(memoryview(m))

        def _close(mm=m):
            try:
                mm.close()
            except BufferError:
                pass  # a view still aliases the map (interpreter exit)

        try:
            weakref.finalize(value, _close)
        except TypeError:
            pass  # scalar/container: mapping lives until GC of m
        return value

    # -- raw byte-level API ---------------------------------------------------

    def create(self, object_id: ObjectID, size: int,
               allow_evict: bool = True) -> memoryview:
        off = ctypes.c_uint64()
        with self._close_lock:
            if not self._handle:
                raise ObjectStoreFullError("store is closed")
            rc = self._lib.rt_create_opts(self._handle, object_id.binary(),
                                          size, ctypes.byref(off),
                                          1 if allow_evict else 0)
        if rc == -17:  # EEXIST
            raise FileExistsError(object_id.hex())
        if rc != 0:
            raise ObjectStoreFullError(
                f"cannot allocate {size} bytes for {object_id.hex()} (rc={rc})"
            )
        return self._view[off.value : off.value + size]

    def seal(self, object_id: ObjectID):
        with self._close_lock:
            if self._handle:
                self._lib.rt_seal(self._handle, object_id.binary())

    def release(self, object_id: ObjectID):
        with self._close_lock:
            if self._handle:
                self._lib.rt_release(self._handle, object_id.binary())

    def abort(self, object_id: ObjectID):
        with self._close_lock:
            if self._handle:
                self._lib.rt_abort(self._handle, object_id.binary())

    def get_buffer(self, object_id: ObjectID) -> Optional[memoryview]:
        """Pin + return buffer view, or None if absent/unsealed."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        with self._close_lock:
            if not self._handle:  # closed (raylet shutdown) — data-plane
                return None       # serve threads may race one request in
            rc = self._lib.rt_get(self._handle, object_id.binary(),
                                  ctypes.byref(off), ctypes.byref(size))
            if rc != 0:
                return None
            return self._view[off.value : off.value + size.value]

    def contains(self, object_id: ObjectID) -> bool:
        with self._close_lock:
            if not self._handle:
                return False
            return bool(self._lib.rt_contains(self._handle,
                                              object_id.binary()))

    def delete(self, object_id: ObjectID) -> bool:
        with self._close_lock:
            ok = bool(self._handle) and \
                self._lib.rt_delete(self._handle, object_id.binary()) == 0
        try:
            os.unlink(self._spill_path(object_id))
            ok = True
        except OSError:
            pass
        return ok

    def stats(self) -> dict:
        st = _StoreStats()
        with self._close_lock:
            if self._handle:
                self._lib.rt_stats(self._handle, ctypes.byref(st))
        return {
            "capacity": st.capacity,
            "bytes_in_use": st.bytes_in_use,
            "num_objects": st.num_objects,
            "num_evictions": st.num_evictions,
        }

    # -- object-level API -----------------------------------------------------

    def put_serialized(self, object_id: ObjectID,
                       ser: serialization.SerializedObject,
                       spill_ok: Optional[bool] = None):
        if spill_ok is None:
            spill_ok = config.object_store_spill
        try:
            # spilling mode never LRU-evicts sealed data: the NEW object
            # overflows to disk instead (no silent loss)
            buf = self.create(object_id, ser.total_bytes(),
                              allow_evict=not spill_ok)
        except ObjectStoreFullError:
            if not spill_ok:
                raise
            self.spill(object_id, ser)
            return
        try:
            ser.write_into(buf)
        except BaseException:
            del buf
            self.abort(object_id)
            raise
        del buf
        self.seal(object_id)
        self.release(object_id)

    def put(self, object_id: ObjectID, value: Any):
        self.put_serialized(object_id, serialization.serialize(value))

    def get(self, object_id: ObjectID, timeout: Optional[float] = None,
            known_sealed: bool = True) -> Any:
        """Deserialize an object; blocks until sealed (bounded by timeout).

        ``known_sealed``: the caller learned from the raylet that the object
        was sealed here — so absence means it was EVICTED (LRU), and we raise
        ObjectLostError immediately instead of polling forever.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.0005
        while True:
            buf = self.get_buffer(object_id)
            if buf is None and not self.contains(object_id) \
                    and self.has_spilled(object_id):
                return self.read_spilled(object_id)
            if buf is None and known_sealed and not self.contains(object_id):
                raise ObjectLostError(object_id)
            if buf is not None:
                try:
                    value = serialization.deserialize(buf)
                except BaseException:
                    del buf
                    self.release(object_id)
                    raise
                if value is None or isinstance(value, (bool, int, float, str, bytes)):
                    # Immutable scalars can't alias shm buffers: unpin now.
                    del buf
                    self.release(object_id)
                else:
                    try:
                        weakref.finalize(value, self.release, object_id)
                    except TypeError:
                        # Containers (tuple/dict/list) aren't weakref-able:
                        # release now; the mapping stays valid so views can
                        # never fault, they just become evictable.
                        del buf
                        self.release(object_id)
                return value
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"object {object_id.hex()} not ready")
            time.sleep(delay)
            delay = min(delay * 2, 0.01)

    def close(self):
        with self._close_lock:
            if self._handle:
                self._view.release()
                self._mmap.close()
                self._lib.rt_store_detach(self._handle)
                self._handle = None


class InProcObjectStore:
    """Pure-Python fallback store (used by local_mode and unit tests)."""

    def __init__(self):
        self._objects = {}

    def put(self, object_id: ObjectID, value: Any):
        self._objects[object_id] = serialization.dumps(value)

    def put_serialized(self, object_id, ser):
        self._objects[object_id] = ser.to_bytes()

    def get(self, object_id: ObjectID, timeout: Optional[float] = None,
            known_sealed: bool = True) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while object_id not in self._objects:
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"object {object_id.hex()} not ready")
            time.sleep(0.001)
        return serialization.loads(self._objects[object_id])

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._objects

    def delete(self, object_id: ObjectID) -> bool:
        return self._objects.pop(object_id, None) is not None

    def stats(self) -> dict:
        return {
            "capacity": 0,
            "bytes_in_use": sum(len(v) for v in self._objects.values()),
            "num_objects": len(self._objects),
            "num_evictions": 0,
        }

    def close(self):
        self._objects.clear()
