"""User-visible exception types (reference: `python/ray/exceptions.py`)."""

from __future__ import annotations


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised an exception; re-raised on ``get`` with the remote
    traceback appended (reference ``RayTaskError``)."""

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(
            f"task {function_name} failed:\n{traceback_str}"
        )

    def __reduce__(self):
        # Exceptions with non-(args)-compatible __init__ need an explicit
        # reduce to survive the control-plane pickle round trip.
        return (TaskError, (self.function_name, self.traceback_str, None))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead; pending and future method calls fail."""

    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"actor {actor_id_hex} died: {reason}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason))


class ObjectLostError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass
