"""User-visible exception types (reference: `python/ray/exceptions.py`)."""

from __future__ import annotations


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised an exception; re-raised on ``get`` with the remote
    traceback appended (reference ``RayTaskError``)."""

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(
            f"task {function_name} failed:\n{traceback_str}"
        )

    def __reduce__(self):
        # Exceptions with non-(args)-compatible __init__ need an explicit
        # reduce to survive the control-plane pickle round trip.
        return (TaskError, (self.function_name, self.traceback_str, None))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead; pending and future method calls fail."""

    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"actor {actor_id_hex} died: {reason}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason))


class ObjectLostError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's end-to-end deadline expired somewhere in the pipeline
    (admission, queue, pre-exec, or mid-exec) — the work was shed or
    interrupted, never silently continued (reference: Serve request
    timeouts + task cancellation semantics).  ``hop`` names where the
    deadline was enforced."""

    def __init__(self, message: str = "deadline exceeded", hop: str = ""):
        self._raw_message = message
        self.hop = hop
        super().__init__(message if not hop
                         else f"{message} (at {hop})")

    def __reduce__(self):
        # reconstruct from the RAW message + hop (the error is always
        # minted worker/raylet-side and pickled to the caller, so
        # dropping hop here would blank the documented dispatch surface)
        return (DeadlineExceededError, (self._raw_message, self.hop))


class TaskCancelledError(RayTpuError):
    """The task was cancelled (``ray_tpu.cancel`` or deadline-driven
    cancel fan-out) before or while it ran (reference
    ``ray.exceptions.TaskCancelledError``)."""

    def __init__(self, message: str = "task was cancelled"):
        super().__init__(message)


class BackPressureError(RayTpuError):
    """The target refused to queue the request: a Serve replica at
    ``max_ongoing_requests``, or a raylet whose bounded ready queue is
    full.  Retryable by the caller — against another replica, or after
    ``Retry-After`` (reference: Serve backpressure / 503 shedding)."""

    def __init__(self, message: str = "request rejected (overloaded)"):
        super().__init__(message)


class OutOfMemoryError(RayTpuError):
    """The worker running the task was OOM-killed by the raylet's memory
    monitor (reference ``ray.exceptions.OutOfMemoryError``): the kill is
    counted against the task's retry budget and the final failure carries
    the crash-forensics log excerpt."""


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass
