"""Typed config/flag registry with environment-variable override.

Mirrors the reference's ``RAY_CONFIG`` macro system
(`src/ray/common/ray_config_def.h:22`, env override at
`src/ray/common/ray_config.h:100`): every flag has a type, a default, and can
be overridden by ``RAY_TPU_<NAME>`` in the environment.  Flags are read at
process start; ``Config.initialize(overrides)`` applies a dict (the launcher
serializes driver-side overrides into worker processes this way, like the
reference serializes its config JSON into every raylet/worker command line).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


class _Flag:
    __slots__ = ("name", "type", "default", "doc", "value")

    def __init__(self, name, type_, default, doc):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc
        env = os.environ.get(_ENV_PREFIX + name.upper())
        if env is not None:
            self.value = _PARSERS[type_](env)
        else:
            self.value = default


class _Config:
    def __init__(self):
        self._flags: Dict[str, _Flag] = {}

    def define(self, name: str, type_: type, default, doc: str = ""):
        self._flags[name] = _Flag(name, type_, default, doc)

    def initialize(self, overrides: Dict[str, Any]):
        for k, v in overrides.items():
            if k in self._flags:
                self._flags[k].value = self._flags[k].type(v)

    def to_dict(self) -> Dict[str, Any]:
        return {k: f.value for k, f in self._flags.items()}

    def serialize(self) -> str:
        return json.dumps(self.to_dict())

    def __getattr__(self, name: str):
        flags = object.__getattribute__(self, "_flags")
        if name in flags:
            return flags[name].value
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._flags[name].value = self._flags[name].type(value)


config = _Config()

# --- core runtime -----------------------------------------------------------
config.define("object_store_memory_mb", int, 512, "Default shm store size.")
config.define("object_store_fallback_inproc", bool, False,
              "Force pure-Python object store (no C++ shm).")
config.define("inline_object_max_bytes", int, 100 * 1024,
              "Objects at or below this size are returned inline over the "
              "control socket instead of through the shm store (reference: "
              "task returns <=100KB are inlined, core_worker.h:988).")
config.define("num_workers_default", int, 0,
              "0 = os.cpu_count() capped by num_cpus.")
config.define("worker_start_timeout_s", float, 30.0, "")
config.define("task_retry_default", int, 3,
              "Default max retries for tasks (reference ray_option_utils.py:149).")
config.define("actor_max_restarts_default", int, 0, "")
config.define("get_timeout_poll_s", float, 0.01, "")
config.define("worker_niceness", int, 0, "")
config.define("log_to_driver", bool, True, "")
config.define("temp_dir", str, "/tmp/ray_tpu", "Session root directory.")
config.define("prestart_workers", bool, True,
              "Start the worker pool eagerly at init (reference raylet "
              "prestarts workers, main.cc:48).")
config.define("dispatch_batch_max", int, 64,
              "Max same-shape normal tasks dispatched to one worker in a "
              "single coalesced frame (they execute sequentially and hold "
              "ONE task's resources; the worker requeues unstarted ones if "
              "its current task blocks).  1 disables batching.  Sized with "
              "the native frame codec: a 64-frame train is one sendall + "
              "one scan, and blocked batches hand their tail back, so the "
              "latency cost of depth is bounded by one task's runtime.")
config.define("actor_pipeline_depth", int, 32,
              "Max calls pipelined to a SYNC max_concurrency=1 actor ahead "
              "of completion (the worker's single executor thread runs "
              "them one at a time, so effective concurrency stays 1; this "
              "just keeps its queue warm instead of paying a socket "
              "round-trip of latency between calls).")
config.define("health_check_period_s", float, 1.0, "")
config.define("task_event_buffer_size", int, 10000,
              "Max buffered task state events for the state API.")

# --- data plane --------------------------------------------------------------
config.define("data_channel", bool, True,
              "Zero-copy raylet-to-raylet data plane: bulk object bytes "
              "move on a dedicated per-peer TCP connection with a raw "
              "binary protocol (data_channel.py) driven by the pull "
              "manager (pull_manager.py).  RAY_TPU_DATA_CHANNEL=0 falls "
              "back to single-source pickled chunks on the control "
              "socket (the pre-data-plane path, kept for parity tests).")

# --- observability -----------------------------------------------------------
config.define("task_events", bool, True,
              "Export task lifecycle events to the GCS task-event table "
              "(reference: GCS task-event backend feeding list_tasks / "
              "ray.timeline).  RAY_TPU_TASK_EVENTS=0 disables the export "
              "(local ring buffers keep working).")
config.define("task_event_flush_interval_s", float, 0.25,
              "Raylet -> GCS task-event batch flush period.")
config.define("task_event_batch_max", int, 512,
              "Flush the task-event export buffer early once it holds this "
              "many events (piggybacks on the frame-train drain cadence).")
config.define("task_event_export_buffer", int, 4096,
              "Ring-buffer cap for not-yet-flushed task events; overflow "
              "drops the OLDEST events and bumps num_dropped — export "
              "backpressure never blocks dispatch.")
config.define("task_events_max_per_job", int, 20000,
              "GCS-side cap per job: max retained task events AND max "
              "tracked per-task states (oldest evicted first).")
config.define("internal_metrics_interval_s", float, 1.0,
              "Flush period for the runtime's own ray_tpu_internal_* "
              "metrics (queue depth, dispatch latency, store bytes, codec "
              "counters) into the metrics KV -> /metrics.  0 disables.")

# --- tensor plane -----------------------------------------------------------
config.define("mesh_default_axes", str, "dp,tp", "")
config.define("enable_pallas", bool, True,
              "Use Pallas kernels on TPU when shapes allow.")
