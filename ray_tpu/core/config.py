"""Typed config/flag registry with environment-variable override.

Mirrors the reference's ``RAY_CONFIG`` macro system
(`src/ray/common/ray_config_def.h:22`, env override at
`src/ray/common/ray_config.h:100`): every flag has a type, a default, and can
be overridden by ``RAY_TPU_<NAME>`` in the environment.  Flags are read at
process start; ``Config.initialize(overrides)`` applies a dict (the launcher
serializes driver-side overrides into worker processes this way, like the
reference serializes its config JSON into every raylet/worker command line).

This registry is the ONLY sanctioned reader of ``RAY_TPU_*`` environment
variables: every knob and per-process identity variable is declared here (or
in its owning module via ``config.define``), and the static-analysis suite
(`tools/analysis`, env-flag-registry pass) rejects direct ``os.environ``
reads of ``RAY_TPU_*`` anywhere else in the package.  The same declarations
generate the env-var reference table in the README
(``python -m tools.analysis --write-env-table``).

Two flavors of flag:

* plain (default): the environment is read ONCE, at ``define()`` time
  (process start) — the reference's read-at-startup semantics.
* ``live=True``: attribute access re-reads the environment on every read.
  Used for per-process identity variables that a parent sets in a child's
  environment (node id, worker profile, session dir) and for test-facing
  knobs flipped via ``monkeypatch.setenv`` after import (chaos injection,
  debug locks).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


class _Flag:
    __slots__ = ("name", "type", "default", "doc", "value", "live",
                 "_env", "_last_raw", "_last_val")

    def __init__(self, name, type_, default, doc, live=False):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc
        self.live = live
        self.value = default
        self._env = _ENV_PREFIX + name.upper()
        # live-read memo: re-parse only when the raw env STRING changes
        # (chaos/debug flags are read per task execution — the parse and
        # the per-read string building were the cost, not the env get)
        self._last_raw = None
        self._last_val = None
        if not live:
            self.reload()

    @property
    def env_name(self) -> str:
        return self._env

    def _parse(self, raw: str):
        # A malformed env value falls back to the current value instead of
        # blowing up whichever import happens to define the flag.
        try:
            return _PARSERS[self.type](raw)
        except (ValueError, TypeError):
            return self.value

    def reload(self):
        """Recompute the stored value: default, then environment override
        (so deleting the env var between reloads restores the default).
        Live flags re-read the environment on every access and never bake
        it into the stored value — reload is a no-op for them."""
        if self.live:
            return
        self.value = self.default
        env = os.environ.get(self.env_name)
        if env is not None:
            self.value = self._parse(env)

    def current(self):
        if self.live:
            env = os.environ.get(self._env)
            if env is not None:
                if env != self._last_raw:
                    self._last_val = self._parse(env)
                    self._last_raw = env
                return self._last_val
        return self.value


class _Config:
    # Non-live flag values are MATERIALIZED as plain instance attributes:
    # ``config.foo`` is then an ordinary instance-dict hit instead of a
    # ``__getattr__`` miss (the miss protocol costs ~1µs and the direct
    # transport hot path reads a dozen flags per call).  Live flags are
    # never materialized — they re-read the environment on every access
    # via the ``__getattr__`` fallback.  Every mutation path (define /
    # initialize / reload / attribute set) re-materializes.

    def __init__(self):
        self._flags: Dict[str, _Flag] = {}

    def define(self, name: str, type_: type, default, doc: str = "",
               live: bool = False):
        flag = _Flag(name, type_, default, doc, live=live)
        self._flags[name] = flag
        if not live:
            object.__setattr__(self, name, flag.value)

    def initialize(self, overrides: Dict[str, Any]):
        for k, v in overrides.items():
            if k in self._flags:
                flag = self._flags[k]
                flag.value = flag.type(v)
                if not flag.live:
                    object.__setattr__(self, k, flag.value)

    def reload(self, *names: str):
        """Re-read environment overrides — all flags, or just ``names``.
        Lets tests (and ``chaos.configure_net``) apply ``setenv`` changes
        made after the defining module was imported."""
        for name in names or list(self._flags):
            flag = self._flags[name]
            flag.reload()
            if not flag.live:
                object.__setattr__(self, name, flag.value)

    def to_dict(self) -> Dict[str, Any]:
        # Live flags are per-process identity (node id, session dir, ...):
        # serializing a driver's identity into a worker would be wrong, so
        # they never ride the override dict.
        return {k: f.value for k, f in self._flags.items() if not f.live}

    def serialize(self) -> str:
        return json.dumps(self.to_dict())

    def __getattr__(self, name: str):
        # only reached for LIVE flags (and genuinely unknown names) —
        # non-live flags are materialized instance attributes
        flags = object.__getattribute__(self, "_flags")
        if name in flags:
            return flags[name].current()
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            flag = self._flags[name]
            flag.value = flag.type(value)
            if not flag.live:
                object.__setattr__(self, name, flag.value)


config = _Config()

# --- core runtime -----------------------------------------------------------
config.define("object_store_memory_mb", int, 512, "Default shm store size.")
config.define("object_store_fallback_inproc", bool, False,
              "Force pure-Python object store (no C++ shm).")
config.define("inline_object_max_bytes", int, 100 * 1024,
              "Objects at or below this size are returned inline over the "
              "control socket instead of through the shm store (reference: "
              "task returns <=100KB are inlined, core_worker.h:988).")
config.define("num_workers_default", int, 0,
              "0 = os.cpu_count() capped by num_cpus.")
config.define("worker_start_timeout_s", float, 30.0, "")
config.define("task_retry_default", int, 3,
              "Default max retries for tasks (reference ray_option_utils.py:149).")
config.define("actor_max_restarts_default", int, 0, "")
config.define("get_timeout_poll_s", float, 0.01, "")
config.define("worker_niceness", int, 0, "")
config.define("log_to_driver", bool, True, "")
config.define("temp_dir", str, "/tmp/ray_tpu", "Session root directory.")
config.define("prestart_workers", bool, True,
              "Start the worker pool eagerly at init (reference raylet "
              "prestarts workers, main.cc:48).")
config.define("dispatch_batch_max", int, 64,
              "Max same-shape normal tasks dispatched to one worker in a "
              "single coalesced frame (they execute sequentially and hold "
              "ONE task's resources; the worker requeues unstarted ones if "
              "its current task blocks).  1 disables batching.  Sized with "
              "the native frame codec: a 64-frame train is one sendall + "
              "one scan, and blocked batches hand their tail back, so the "
              "latency cost of depth is bounded by one task's runtime.")
config.define("actor_pipeline_depth", int, 32,
              "Max calls pipelined to a SYNC max_concurrency=1 actor ahead "
              "of completion (the worker's single executor thread runs "
              "them one at a time, so effective concurrency stays 1; this "
              "just keeps its queue warm instead of paying a socket "
              "round-trip of latency between calls).")
config.define("health_check_period_s", float, 1.0, "")
config.define("task_event_buffer_size", int, 10000,
              "Max buffered task state events for the state API.")

# --- overload protection & deadlines ----------------------------------------
config.define("deadlines", bool, True,
              "Kill switch for the end-to-end deadline machinery: "
              "RAY_TPU_DEADLINES=0 makes deadline_s/request_timeout_s "
              "no-ops (specs carry no deadline, nothing is shed or "
              "interrupted on expiry) — today's pre-deadline behavior.")
config.define("max_queue_depth", int, 0,
              "Bounded raylet queues: above this many queued tasks "
              "(ready queue, or one actor's call queue) new admissions "
              "shed the lowest-deadline-headroom task with a typed "
              "BackPressureError instead of queueing without limit "
              "(reference: bounded lease queues + Serve backpressure).  "
              "0 = unbounded (default).")

# --- data plane --------------------------------------------------------------
config.define("data_channel", bool, True,
              "Zero-copy raylet-to-raylet data plane: bulk object bytes "
              "move on a dedicated per-peer TCP connection with a raw "
              "binary protocol (data_channel.py) driven by the pull "
              "manager (pull_manager.py).  RAY_TPU_DATA_CHANNEL=0 falls "
              "back to single-source pickled chunks on the control "
              "socket (the pre-data-plane path, kept for parity tests).")

# --- observability -----------------------------------------------------------
config.define("task_events", bool, True,
              "Export task lifecycle events to the GCS task-event table "
              "(reference: GCS task-event backend feeding list_tasks / "
              "ray.timeline).  RAY_TPU_TASK_EVENTS=0 disables the export "
              "(local ring buffers keep working).")
config.define("task_event_flush_interval_s", float, 0.25,
              "Raylet -> GCS task-event batch flush period.")
config.define("task_event_batch_max", int, 512,
              "Flush the task-event export buffer early once it holds this "
              "many events (piggybacks on the frame-train drain cadence).")
config.define("task_event_export_buffer", int, 4096,
              "Ring-buffer cap for not-yet-flushed task events; overflow "
              "drops the OLDEST events and bumps num_dropped — export "
              "backpressure never blocks dispatch.")
config.define("task_events_max_per_job", int, 20000,
              "GCS-side cap per job: max retained task events AND max "
              "tracked per-task states (oldest evicted first).")
config.define("internal_metrics_interval_s", float, 1.0,
              "Flush period for the runtime's own ray_tpu_internal_* "
              "metrics (queue depth, dispatch latency, store bytes, codec "
              "counters) into the metrics KV -> /metrics.  0 disables.")
config.define("metrics_table_max", int, 20000,
              "GCS-side cap per NODE on retained metric time-series "
              "points (add_metric_points / query_metrics); oldest "
              "evicted first, evictions counted in metrics_table_stats.")

# --- alerting ----------------------------------------------------------------
config.define("alerts", bool, True,
              "Evaluate alert rules in the GCS on the metrics flush "
              "cadence (RAY_TPU_ALERTS=0 disables the rule engine; the "
              "alert table and list_alerts keep working, nothing new "
              "fires).")
config.define("alerts_eval_interval_s", float, 2.0,
              "Period between alert rule evaluations in the GCS health "
              "monitor.")
config.define("alerts_table_max", int, 1000,
              "GCS-side cap on retained alert records (firing/resolved "
              "transitions); oldest evicted first, evictions counted.")
config.define("alerts_rules", str, "",
              "Extra alert rules as a JSON list of rule dicts, merged "
              "over (and by name overriding) the built-in defaults "
              "(util.alerts.default_rules); re-read on every evaluation "
              "so tests can inject rules live.", live=True)
config.define("alerts_default_rules", bool, True,
              "Ship the built-in default rule set (false-suspect rate, "
              "fenced-frame spikes, replication-repair pressure, Serve "
              "shed-ratio burn rate, telemetry drop counters).  0 leaves "
              "only RAY_TPU_ALERTS_RULES rules active.")

# --- tensor plane -----------------------------------------------------------
config.define("mesh_default_axes", str, "dp,tp", "")
config.define("enable_pallas", bool, True,
              "Use Pallas kernels on TPU when shapes allow.")

# --- process identity (live: set by a parent in the child's environment) ----
config.define("address", str, "",
              "Cluster address auto-attached by ray_tpu.init() when no "
              "address argument is given (reference: RAY_ADDRESS); set by "
              "the job manager for submitted entrypoints.", live=True)
config.define("node_id", str, "",
              "Hosting raylet's node id, set in every spawned worker's "
              "environment (runtime_context.get_node_id on workers).",
              live=True)
config.define("job_id", str, "driver",
              "Job attribution for task events: the job supervisor sets "
              "this in the entrypoint's environment before the driver "
              "starts (read once at import); ad-hoc drivers share one "
              "'driver' bucket.")
config.define("session_dir", str, "",
              "Session directory, set in spawned workers' environment by "
              "their raylet (log files, runtime-env staging).", live=True)
config.define("node_ip", str, "",
              "Hosting node's IP, set in spawned workers' environment by "
              "a cluster-mode raylet; a worker that sees it also listens "
              "on TCP for direct worker→worker calls from peers.",
              live=True)
config.define("node_incarnation", int, 0,
              "Hosting node's registration incarnation at worker spawn "
              "time (the PR 8 fencing token), set in the worker's "
              "environment; direct-call hellos presenting an OLDER "
              "incarnation are rejected as fenced.", live=True)
config.define("worker_profile", str, "cpu",
              "Worker-pool profile this worker process was spawned for "
              "(set by the raylet; read back at register time).", live=True)
config.define("worker_id", str, "",
              "TPU worker index within a pod slice (topology label "
              "tpu_worker_id; TPU_WORKER_ID is the non-test source).",
              live=True)
config.define("actor_restarts", int, 0,
              "Restart count the raylet stamps into a restarted actor "
              "worker's environment (was_current_actor_reconstructed).",
              live=True)
config.define("num_chips", int, 0,
              "TPU chip count to advertise as this node's TPU resource "
              "(overrides jax device discovery).", live=True)
config.define("gcs_address", str, "",
              "GCS host:port for autoscaler-provisioned nodes: the "
              "instance startup script exports it and hands it to "
              "`ray_tpu start`.", live=True)
config.define("node_type", str, "",
              "Autoscaler node-type name of a provisioned instance "
              "(exported by its startup script).", live=True)
config.define("accelerator_type", str, "",
              "Accelerator type topology label (e.g. v5e-8); test "
              "override for TPU_ACCELERATOR_TYPE.", live=True)
config.define("slice_id", str, "",
              "Pod-slice identity topology label (tpu_slice): nodes "
              "sharing it are ICI-adjacent; test override for TPU_NAME.",
              live=True)
config.define("topology", str, "",
              "Slice topology label (e.g. 2x4); test override for "
              "TPU_TOPOLOGY.", live=True)

# --- developer tooling ------------------------------------------------------
config.define("debug_locks", bool, False,
              "Runtime lock-order watchdog: util.locks.make_lock() returns "
              "DebugLock wrappers that record per-thread lock acquisition "
              "order into a global graph and report potential-deadlock "
              "cycles with the stacks of both orderings.  On for the test "
              "suite in CI.", live=True)
