"""Control-plane wire protocol: length-prefixed pickled frames over unix
sockets.

Reference analogue: Ray uses gRPC for worker<->raylet control
(`node_manager.proto`) and a unix socket with flatbuffers for the local
raylet connection (`src/ray/raylet/format/node_manager.fbs`).  Single-node
round 1 uses one unix stream socket per worker; the multi-node transport
(gRPC across hosts) slots in behind the same message schema.

Message = arbitrary picklable dict with a "t" (type) key.  Types:

driver->worker:
  task          {spec: TaskSpec, arg_values: {hex: bytes}}   dispatch
  reply         {rid, ok, value|error}                       response to a request
  shutdown      {}

worker->driver:
  register      {pid, worker_id}
  done          {task_id, ok, inline: {hex: bytes}, stored: [hex], error}
  submit        {spec}                                       nested submission
  request       {rid, op, ...}  ops: get / wait / put_inline / kv_get / kv_put /
                actor_handle / named_actor / submit_sync / log
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, Optional

_LEN = struct.Struct("<Q")


def send_msg(sock: socket.socket, msg: Any, lock=None):
    data = pickle.dumps(msg, protocol=5)
    frame = _LEN.pack(len(data)) + data
    if lock is not None:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def send_msgs(sock: socket.socket, msgs, lock=None):
    """Concatenate many frames into ONE sendall.

    The receiver's recv_msg parses length-prefixed frames one at a time, so
    coalescing is invisible to it.  The point is the syscall count: on a
    busy host each sendall to a blocked peer costs a scheduler wakeup
    (~100us measured on a contended 1-vCPU box) — one write for a 16-task
    dispatch batch pays that once instead of 16 times."""
    if not msgs:
        return
    parts = []
    for msg in msgs:
        data = pickle.dumps(msg, protocol=5)
        parts.append(_LEN.pack(len(data)))
        parts.append(data)
    frame = b"".join(parts)
    if lock is not None:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def drain_frames(buf: bytearray, handle, alive) -> None:
    """Handle every complete length-prefixed frame in ``buf`` (the
    receive-side counterpart of send_msgs' coalescing); stops early —
    leaving the rest buffered — when ``alive()`` goes false, so a handler
    may kill or repurpose the connection mid-train."""
    hdr = _LEN.size
    while alive():
        if len(buf) < hdr:
            return
        (length,) = _LEN.unpack_from(buf)
        if len(buf) < hdr + length:
            return
        msg = pickle.loads(bytes(buf[hdr:hdr + length]))
        del buf[:hdr + length]
        handle(msg)


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Optional[Any]:
    header = recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    data = recv_exact(sock, length)
    if data is None:
        return None
    return pickle.loads(data)
