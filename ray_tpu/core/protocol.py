"""Control-plane wire protocol: length-prefixed pickled frames over unix
sockets.

Reference analogue: Ray uses gRPC for worker<->raylet control
(`node_manager.proto`) and a unix socket with flatbuffers for the local
raylet connection (`src/ray/raylet/format/node_manager.fbs`).  Single-node
round 1 uses one unix stream socket per worker; the multi-node transport
(gRPC across hosts) slots in behind the same message schema.

BULK DATA does not ride this protocol: raylet-to-raylet object bytes move
on a dedicated per-peer-pair TCP connection with a raw binary header
format (see ``data_channel.py``) so control frames never queue behind
megabytes of payload.  Only the python-fallback pull path (and inline
objects) still ship object bytes as pickled control frames.

Message = arbitrary picklable dict with a "t" (type) key.  Types:

driver->worker:
  task          {spec: TaskSpec, arg_values: {hex: bytes}}   dispatch
  reply         {rid, ok, value|error}                       response to a request
  shutdown      {}

worker->driver:
  register      {pid, worker_id, direct_addr}
  done          {task_id, ok, inline: {hex: bytes}, stored: [hex], error}
  direct_done   done + {spec} — bookkeeping for a call whose result already
                reached the caller over a direct channel
  direct_notes  {notes: [direct_running|direct_done, ...]} — one coalesced
                train of direct bookkeeping notes (burst mode), applied in
                order raylet-side
  submit        {spec}                                       nested submission
  request       {rid, op, ...}  ops: get / wait / put_inline / kv_get / kv_put /
                actor_handle / named_actor / submit_sync / log /
                direct_lookup / direct_lease / direct_lease_release

raylet->worker (direct-transport control):
  direct_lease  {lease_id|None}  lease token grant/release — the worker's
                DirectServer rejects lease hellos presenting any other id
  direct_fence  {actor_ids, node_id}  tear down matching direct channels

direct channel (caller worker <-> callee worker, core/direct.py — the
raylet is NOT on this path; it only brokered the address):
  dhello        {caller, actor_id|None, generation, incarnation, lease_id}
  dhello_ack    {ok, reason, pid}      generation/incarnation fencing verdict
  dcall         {spec}                 FIFO per channel; dep-free specs only
  dburst        {calls: [dcall|dcancel, ...]}  one coalesced submit flush
                window (burst mode) — pickled as a single frame so shared
                spec strings are memoized across the burst; unpacked in
                order at the callee
  dresult       {task_id, ok, inline, stored, sizes, error, rejected?,
                 dur?}  dur = callee decode→result turnover (burst mode),
                the caller's lease-pipelining evidence
  dcancel       {task_id}              cancel a call submitted on this
                channel (pre-exec mark / mid-exec interrupt)

Codec layer: framing (scan on receive, coalesced assembly on send) is a
pluggable codec.  The default is a native library
(`ray_tpu/native/src/frame_codec.cc`, same hermetic g++ + ctypes recipe as
the shm object store) that returns every complete frame's boundaries in ONE
GIL-cheap call per socket-readiness event; a byte-identical pure-Python
codec is selected automatically when the native build is unavailable, or
forced with ``RAY_TPU_DISABLE_NATIVE_CODEC=1``.  The reference pays the
equivalent cost in GIL-released Cython (`_raylet.pyx:3111`).
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, Callable, List, Optional, Tuple

from ray_tpu.core.config import config

config.define("disable_native_codec", bool, False,
              "Force the pure-Python frame codec even when the native "
              "library is available (parity tests, debugging).  Consumed "
              "once, when the codec singleton is built at import.")

_LEN = struct.Struct("<Q")
_HDR = _LEN.size

# Stream-corruption guard: a frame claiming more than this is a desynced or
# hostile peer, not a real message (inline objects cap at ~100KB, pull
# chunks at a few MB; the biggest legitimate frames are runtime-env
# working-dir zips riding KV puts).  Matches the reference's 512MB gRPC
# message ceiling — low enough that a corrupt length prefix is rejected
# BEFORE recv_exact allocates a receive buffer for it.  Both codecs
# reject identically.
MAX_FRAME_BYTES = 1 << 29


class ProtocolError(RuntimeError):
    """Framing-level corruption (oversized length prefix).  The connection
    that produced it must be torn down — the stream cannot resync."""


# ---------------------------------------------------------------------------
# Codecs: scan (receive side) and encode (send side).  Both produce/consume
# byte-identical streams; tests/test_protocol_codec.py fuzzes the parity.


class PythonCodec:
    """Pure-Python fallback — also the reference semantics for the tests."""

    name = "python"

    @staticmethod
    def scan(view, length: int) -> Tuple[List[Tuple[int, int]], int]:
        """Return ([(payload_off, payload_len), ...], consumed) for every
        complete frame in ``view[:length]``.  ``view`` is any object
        supporting ``unpack_from`` access (bytes/bytearray/memoryview)."""
        frames: List[Tuple[int, int]] = []
        pos = 0
        unpack_from = _LEN.unpack_from
        while length - pos >= _HDR:
            (flen,) = unpack_from(view, pos)
            if flen > MAX_FRAME_BYTES:
                raise ProtocolError(
                    f"frame length {flen} exceeds {MAX_FRAME_BYTES}")
            if length - pos - _HDR < flen:
                break
            frames.append((pos + _HDR, flen))
            pos += _HDR + flen
        return frames, pos

    @staticmethod
    def encode(payloads: List[bytes]) -> bytes:
        pack = _LEN.pack
        parts: List[bytes] = []
        for data in payloads:
            parts.append(pack(len(data)))
            parts.append(data)
        return b"".join(parts)


class NativeCodec:
    """ctypes wrapper over librt_codec.so (see frame_codec.cc)."""

    name = "native"

    def __init__(self, path: str):
        import ctypes

        self._ctypes = ctypes
        lib = ctypes.CDLL(path)
        lib.rtc_scan.restype = ctypes.c_longlong
        lib.rtc_scan.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rtc_encode.restype = ctypes.c_longlong
        lib.rtc_encode.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ]
        self._lib = lib
        self._cap = 512

    def scan(self, view, length: int) -> Tuple[List[Tuple[int, int]], int]:
        ctypes = self._ctypes
        if isinstance(view, bytearray):
            # Zero-copy: a live export on the bytearray held only for the
            # duration of this call (the caller compacts after we return).
            arr = (ctypes.c_char * length).from_buffer(view)
        else:
            if isinstance(view, memoryview):
                view = bytes(view[:length])
            arr = (ctypes.c_char * length).from_buffer_copy(view[:length])
        addr = ctypes.addressof(arr)
        cap = self._cap
        frames: List[Tuple[int, int]] = []
        base = 0
        offs = (ctypes.c_uint64 * cap)()
        lens = (ctypes.c_uint64 * cap)()
        consumed = ctypes.c_uint64()
        while True:
            got = self._lib.rtc_scan(
                addr + base, length - base, MAX_FRAME_BYTES, offs, lens,
                cap, ctypes.byref(consumed))
            if got < 0:
                raise ProtocolError(
                    f"frame length exceeds {MAX_FRAME_BYTES}")
            for i in range(got):
                frames.append((base + offs[i], lens[i]))
            base += consumed.value
            if got < cap:
                del arr  # release the bytearray export
                return frames, base

    # Below this total the ctypes argument marshalling costs more than it
    # saves; bytes.join is one C-level pass and wins.  Measured on the dev
    # host: join ahead up to 64KB-frame batches (16x64KB = 1MB total took
    # 221us join vs 136us native), native ~3x faster at 1MB frames.  256KB
    # sits past the measured break-even with margin so small control
    # trains never pay the marshalling overhead.
    _NATIVE_ENCODE_MIN_BYTES = 256 << 10

    def encode(self, payloads: List[bytes]):
        n = len(payloads)
        total = _HDR * n
        for data in payloads:
            total += len(data)
        if total < self._NATIVE_ENCODE_MIN_BYTES:
            return PythonCodec.encode(payloads)
        ctypes = self._ctypes
        out = bytearray(total)
        ptrs = (ctypes.c_char_p * n)(*payloads)
        lens = (ctypes.c_uint64 * n)()
        for i, data in enumerate(payloads):
            lens[i] = len(data)
        dest = (ctypes.c_char * total).from_buffer(out)
        wrote = self._lib.rtc_encode(
            ptrs, lens, n, ctypes.addressof(dest), total)
        del dest  # release the bytearray export before handing `out` off
        if wrote != total:
            raise ProtocolError("native encode overflow (codec bug)")
        return out


def _select_codec():
    if config.disable_native_codec:
        return PythonCodec()
    from ray_tpu.native.build import try_lib_path

    path = try_lib_path("codec")
    if path is None:
        return PythonCodec()
    try:
        return NativeCodec(path)
    except OSError:
        return PythonCodec()


_codec = _select_codec()
NATIVE_CODEC_ACTIVE = _codec.name == "native"


# ---------------------------------------------------------------------------
# Send side


def send_msg(sock: socket.socket, msg: Any, lock=None):
    data = pickle.dumps(msg, protocol=5)
    frame = _LEN.pack(len(data)) + data
    if lock is not None:
        with lock:
            # blocking-ok: the caller-passed lock exists to serialize
            # writers on this one socket (frame integrity); it guards no
            # other state, so nothing else can queue behind the send.
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def send_msgs(sock: socket.socket, msgs, lock=None):
    """Coalesce many frames into ONE sendall.

    The receiver's frame scanner parses length-prefixed frames one at a
    time, so coalescing is invisible to it.  The point is the syscall
    count: on a busy host each sendall to a blocked peer costs a scheduler
    wakeup (~100us measured on a contended 1-vCPU box) — one write for a
    16-task dispatch batch pays that once instead of 16 times.  The frame
    assembly itself (headers + payload memcpy) runs in the native codec
    when available."""
    if not msgs:
        return
    payloads = [pickle.dumps(msg, protocol=5) for msg in msgs]
    frame = _codec.encode(payloads)
    if lock is not None:
        with lock:
            # blocking-ok: per-socket write-serialization lock (see
            # send_msg above); guards no other state.
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def encode_frames(payloads: List[bytes]):
    """Assemble pre-pickled payloads into one wire buffer (codec-routed)."""
    return _codec.encode(payloads)


# ---------------------------------------------------------------------------
# Receive side


def drain_frames(buf: bytearray, handle, alive) -> None:
    """Handle every complete length-prefixed frame in ``buf`` (the
    receive-side counterpart of send_msgs' coalescing); stops early —
    leaving the rest buffered — when ``alive()`` goes false, so a handler
    may kill or repurpose the connection mid-train.

    One codec scan finds every frame boundary up front; payloads are
    unpickled straight out of a memoryview (no per-frame bytes() copy) and
    the buffer is compacted ONCE per drain (the old per-frame
    ``del buf[:k]`` was an O(buffer) memmove each time — quadratic under
    coalesced bursts)."""
    frames, _ = _codec.scan(buf, len(buf))
    if not frames:
        return
    consumed = 0
    mv = memoryview(buf)
    try:
        for off, flen in frames:
            if not alive():
                break
            # A frame counts as consumed once parsed, even if its handler
            # raises (matches the old semantics: a poison message never
            # re-delivers).
            consumed = off + flen
            msg = pickle.loads(mv[off:off + flen])
            handle(msg)
    finally:
        mv.release()
        del buf[:consumed]


def recv_exact(sock: socket.socket, n: int) -> Optional[bytearray]:
    """Read exactly n bytes via recv_into on one preallocated buffer (one
    allocation per message instead of per-chunk bytes + b"".join)."""
    out = bytearray(n)
    view = memoryview(out)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            return None
        got += r
    return out


def recv_into_exact(sock: socket.socket, view: memoryview) -> bool:
    """Fill ``view`` completely via recv_into; False on EOF.  Shared by the
    control-plane readers and the zero-copy data channel (which recv_intos
    straight into shm store buffers — see data_channel.py)."""
    got, n = 0, len(view)
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            return False
        got += r
    return True


def liveness_ping(address, node_id: str, incarnation: int,
                  timeout: float) -> bool:
    """Dial a raylet control listener and verify a ping/pong identity
    echo: the pong must carry the expected node_id AND incarnation — a
    recycled port answering, or an older incarnation of the node, is not
    liveness.  One blocking dial+roundtrip bounded by ``timeout``; shared
    by the GCS's direct probe and the peer-relayed indirect probe so the
    two verdicts can never diverge."""
    timeout = max(0.05, timeout)
    try:
        with socket.create_connection(tuple(address),
                                      timeout=timeout) as sock:
            sock.settimeout(timeout)
            send_msg(sock, {"t": "ping"})
            reply = recv_msg(sock)
    except (OSError, ProtocolError):
        return False
    return (isinstance(reply, dict) and reply.get("t") == "pong"
            and reply.get("node_id") == node_id
            and reply.get("incarnation") == incarnation)


def recv_msg(sock: socket.socket) -> Optional[Any]:
    header = recv_exact(sock, _HDR)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame length {length} exceeds {MAX_FRAME_BYTES}")
    data = recv_exact(sock, length)
    if data is None:
        return None
    return pickle.loads(data)


class FrameReader:
    """Buffered blocking message reader for dedicated reader threads
    (worker <- raylet, GCS server/client loops).

    ``recv_msg`` on a coalesced train previously cost two syscalls and two
    allocations PER MESSAGE (header read + payload read + join).  This
    reader recvs into one reusable chunk, scans every complete frame with
    the codec, and decodes the whole train — so an N-message burst costs
    ~1 syscall, and only partial tails are ever copied into the carry
    buffer."""

    __slots__ = ("_sock", "_chunk", "_buf", "_pending")

    def __init__(self, sock: socket.socket, chunk_size: int = 1 << 20):
        self._sock = sock
        self._chunk = bytearray(chunk_size)
        self._buf = bytearray()  # partial-frame carry
        from collections import deque

        self._pending = deque()

    def _decode(self, view, frames) -> None:
        loads = pickle.loads
        append = self._pending.append
        for off, flen in frames:
            append(loads(view[off:off + flen]))

    def recv_msg(self) -> Optional[Any]:
        """Next message, or None on EOF."""
        if self._pending:
            return self._pending.popleft()
        while True:
            try:
                n = self._sock.recv_into(self._chunk)
            except OSError:
                return None
            if n == 0:
                return None
            if not self._buf:
                # Fast path: scan the fresh chunk in place; only a trailing
                # partial frame (if any) is copied into the carry buffer.
                frames, consumed = _codec.scan(self._chunk, n)
                if frames:
                    self._decode(memoryview(self._chunk), frames)
                if consumed < n:
                    self._buf += memoryview(self._chunk)[consumed:n]
            else:
                self._buf += memoryview(self._chunk)[:n]
                frames, consumed = _codec.scan(self._buf, len(self._buf))
                if frames:
                    mv = memoryview(self._buf)
                    try:
                        self._decode(mv, frames)
                    finally:
                        mv.release()
                    del self._buf[:consumed]
            if self._pending:
                return self._pending.popleft()
