"""ObjectRef — the distributed future handle.

Reference analogue: ``ray.ObjectRef`` (Cython, `python/ray/includes/object_ref.pxi`).
Holds only the ObjectID; resolution goes through the per-process worker
(`ray_tpu.core.worker`).  Refs are picklable and can be passed as task args
(dependency) or stored inside other objects (borrowing — round 1 keeps the
owner as the driver, so serializing a ref is just shipping its ID).
"""

from __future__ import annotations

import threading

from ray_tpu.core.ids import ObjectID

# Serialization-time ref collection (the borrow-pinning protocol's first
# half): while a collector is installed on this thread, every ObjectRef
# pickled records its id.  The serializer returns those ids alongside the
# bytes, and whatever entity comes to OWN the bytes (an object entry, a
# task spec) pins the inner objects until it is itself released — so a ref
# travelling inside a serialized value can never be freed out from under
# the eventual deserializer (reference: borrowed-ref tracking,
# `src/ray/core_worker/reference_count.h:233`).
_collect = threading.local()


class collect_serialized_refs:
    """Context manager installing a per-thread inner-ref collector."""

    def __init__(self):
        self.ids = []

    def __enter__(self):
        self._prev = getattr(_collect, "sink", None)
        _collect.sink = self.ids
        return self

    def __exit__(self, *exc):
        _collect.sink = self._prev
        return False


class ObjectRef:
    """Reference-counted handle: every live ObjectRef in a process counts
    one local reference; when a process's count for an object drops to
    zero it notifies the raylet, which frees the object once NO process
    holds it and no queued task depends on it (reference: distributed ref
    counting, `src/ray/core_worker/reference_count.h:61` — minus the full
    borrowing protocol: refs stashed inside long-lived actor state on
    OTHER nodes must be kept alive by the creator or `ray_tpu.put`)."""

    __slots__ = ("_id", "__weakref__")

    def __init__(self, object_id: ObjectID):
        self._id = object_id
        from ray_tpu.core import worker as _w

        _w.note_ref_created(object_id)

    def __del__(self):
        try:
            from ray_tpu.core import worker as _w

            _w.note_ref_dropped(self._id)
        except Exception:  # noqa: BLE001 interpreter teardown
            pass

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures

        from ray_tpu.core import worker as _w

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(_w.global_worker().get([self])[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_resolve, name="objref-resolve",
                         daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        sink = getattr(_collect, "sink", None)
        if sink is not None:
            sink.append(self._id)
        return (ObjectRef, (self._id,))


class ObjectRefGenerator:
    """Iterator over a streaming task's yields (reference:
    ``ObjectRefGenerator``, `python/ray/_raylet.pyx:209`): each ``next()``
    blocks until the producer has yielded item *i* (it can be consumed
    while the task is still running), then returns the item's ObjectRef.
    """

    def __init__(self, task_id):
        self._task_id = task_id
        self._index = 0
        self._done = False

    @property
    def task_id(self):
        return self._task_id

    def completed(self) -> "ObjectRef":
        """Ref that resolves (to the item count) when the stream finishes."""
        from ray_tpu.core.ids import ObjectID

        return ObjectRef(ObjectID.for_task_return(self._task_id, 0))

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        if self._done:
            raise StopIteration
        from ray_tpu.core import worker as _w
        from ray_tpu.core.ids import ObjectID

        res = _w.global_worker().stream_next(self._task_id, self._index)
        kind = res["kind"]
        if kind == "end":
            self._done = True
            raise StopIteration
        if kind == "error":
            self._done = True
            raise res["error"]
        ref = ObjectRef(
            ObjectID.for_task_return(self._task_id, self._index + 1))
        self._index += 1
        return ref

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()}@{self._index})"
