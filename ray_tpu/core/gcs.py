"""GCS — the cluster control plane (head service).

Reference analogue: the GCS server (`src/ray/gcs/gcs_server/gcs_server.h:78`)
with its node / actor / KV / function / object-directory tables
(`gcs_node_manager`, `gcs_actor_manager.cc`, `gcs_kv_manager`), the GCS
client accessors (`src/ray/gcs/gcs_client/accessor.h:40`), and the
health-check manager (`gcs_health_check_manager.h`).

Re-designed for this runtime: one ``GcsCore`` object owns every table behind
a single lock (the tables are dict operations — there is nothing to gain
from an event loop), with three access paths:

  * embedded  — the single-node default: the driver's in-process raylet holds
    a direct reference to ``GcsCore`` (zero-cost control plane);
  * ``GcsServer`` — a TCP server exposing the same surface over the framed
    pickle protocol (`ray_tpu/core/protocol.py`), one thread per connection
    (node counts are small; the data plane never flows through the GCS);
  * ``GcsClient`` — socket client with an identical duck-typed method
    surface, so the raylet code does not know which one it holds.

Pushes (reference: `src/ray/pubsub/`): subscribers receive node membership
events and object-directory watch notifications. The object directory is
location metadata only — object bytes move raylet-to-raylet (see
`raylet.py` pull protocol), matching the reference's split between the GCS
and the object manager (`src/ray/object_manager/object_manager.h:117`).
"""

from __future__ import annotations

import os
import random
import socket
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import protocol
from ray_tpu.core.config import config
from ray_tpu.util import profiling
from ray_tpu.util.locks import make_lock, make_rlock

config.define("gcs_heartbeat_interval_s", float, 0.25,
              "Raylet -> GCS resource heartbeat period.")
config.define("gcs_restart_reconcile_s", float, 5.0,
              "After a GCS restart, how long raylets get to reconnect "
              "before actors/PG bundles referencing never-returning nodes "
              "are reconciled (actors -> dead, bundles -> re-placed).")
config.define("gcs_node_timeout_s", float, 3.0,
              "Heartbeat silence after which a node is declared dead "
              "with no probe verdict — the HARD fallback behind the "
              "suspicion machine (reference: health check manager "
              "timeouts).")
config.define("gcs_node_suspect_s", float, 0.5,
              "Heartbeat silence after which a node is marked SUSPECT "
              "and actively probed (direct TCP ping plus one indirect "
              "probe via a peer raylet).  Probe failure confirms DEAD "
              "well before gcs_node_timeout_s; probe success resets the "
              "suspicion.  SUSPECT is propagated on the node-change "
              "pubsub so schedulers/pulls route around the node without "
              "triggering recovery (reference: the health-check "
              "manager's ping layer over heartbeats).")
config.define("gcs_probe_timeout_s", float, 0.4,
              "Connect/read timeout for one liveness probe attempt "
              "(direct or relayed through a peer raylet).")
config.define("gcs_probe_enabled", bool, True,
              "Active probing of SUSPECT nodes.  Off: detection falls "
              "back to the plain gcs_node_timeout_s heartbeat silence.")
config.define("drain_timeout_s", float, 30.0,
              "Default graceful-drain deadline: how long a draining "
              "raylet gets to migrate sole-copy objects out, "
              "checkpoint-and-relocate checkpointable actors, and wait "
              "for running tasks before it reports drain_complete "
              "regardless (reference: the autoscaler's DrainNode "
              "deadline).")


class GcsCore:
    """All control-plane tables. Thread-safe; no I/O of its own beyond the
    optional persistence snapshots.

    Persistence (reference: the GCS store clients —
    `src/ray/gcs/store_client/redis_store_client.h:33` for fault
    tolerance, `in_memory_store_client.h:31` otherwise): with
    ``persist_path`` set, the DURABLE tables (kv, functions, actors,
    named actors, placement groups) snapshot to disk on mutation
    (dirty-flag + background flusher, atomic rename) and reload on
    construction.  Node membership and the object directory are SOFT
    state: raylets re-register and re-publish object locations when they
    reconnect after a GCS restart (the reference's raylet↔GCS reconnect
    protocol, `test_gcs_fault_tolerance.py`)."""

    def __init__(self, persist_path: Optional[str] = None):
        self._lock = make_rlock("gcs.core")
        self._persist_path = persist_path
        self._dirty = False  # guard: _lock
        self._flush_lock = make_lock("gcs.snapshot")
        # node_id(hex) -> {address:(host,port)|None, resources_total,
        #                  resources_available, store_path, alive,
        #                  last_heartbeat, hostname}
        self._nodes: Dict[str, dict] = {}  # guard: _lock
        self._kv: Dict[Tuple[str, bytes], bytes] = {}  # guard: _lock
        self._functions: Dict[bytes, bytes] = {}  # guard: _lock
        # actor_id(bytes) -> {owner_node, state, name, namespace, spec_blob}
        self._actors: Dict[bytes, dict] = {}  # guard: _lock
        self._named: Dict[Tuple[str, str], bytes] = {}  # guard: _lock
        # cluster placement groups: pg_id -> {bundles, strategy,
        #   assignments: {bundle_idx: node_id}, origin, pending, state}
        self._cluster_pgs: Dict[str, dict] = {}  # guard: _lock
        # Task-event table (reference: the GCS task-event backend behind
        # `list_tasks`/`ray.timeline`, `python/ray/util/state/api.py:1009`):
        # job_id -> {"events": deque (raw log, timeline), "tasks": dict
        # task_id(hex) -> latest event (state API)}.  Bounded per job
        # (config.task_events_max_per_job), soft state — never persisted.
        self._task_events: Dict[str, dict] = {}  # guard: _lock
        self._task_events_dropped = 0  # guard: _lock
        # Trace-span table (request-flow tracing): job_id -> deque of span
        # records, bounded per job like the task-event table; producer-side
        # drops (raylet export buffers) and GCS-side evictions both count.
        # Soft state — never persisted.
        self._trace_spans: Dict[str, deque] = {}  # guard: _lock
        self._trace_dropped = 0  # guard: _lock
        # Profile table (continuous profiling): node_id -> deque of folded
        # stack-sample records, bounded per node (config.profile_table_max);
        # producer-side drops and GCS-side evictions both count.  Soft
        # state — never persisted.
        self._profile_samples: Dict[str, deque] = {}  # guard: _lock
        self._profile_dropped = 0  # guard: _lock
        # Metrics time-series table: node_id -> deque of timestamped DELTA
        # points (see metrics.collect_points), bounded per node
        # (config.metrics_table_max); producer-side ring drops and GCS-side
        # evictions both count.  Soft state — never persisted.
        self._metric_points: Dict[str, deque] = {}  # guard: _lock
        self._metric_points_dropped = 0  # guard: _lock
        # Alert table: transition log (firing/resolved records) bounded by
        # config.alerts_table_max, plus the live currently-firing view.
        # Only the health-monitor thread evaluates rules; readers snapshot
        # under _lock.
        self._alerts_log: deque = deque()  # guard: _lock
        self._alerts_active: Dict[str, dict] = {}  # guard: _lock
        self._alerts_dropped = 0  # guard: _lock
        # token -> {"event": Event, "reports": {node_id: payload}, "want"}
        # for targeted node queries (live stack dumps, log listings)
        # relayed through the node pubsub; replies land via the
        # node_query_report op — same shape as the indirect-probe waiters.
        self._query_waiters: Dict[str, dict] = {}  # guard: _lock
        self._query_seq = 0  # guard: _lock
        # oid(hex) -> {nodes: set[node_id], size, inline}
        self._objects: Dict[str, dict] = {}  # guard: _lock
        # oid(hex) -> set of watcher node_ids (want a push when located)
        self._object_watchers: Dict[str, set] = {}  # guard: _lock
        # subscribers: (node_id_or_None, callback(event, data))
        self._subs: List[Tuple[Optional[str], Callable[[str, Any], None]]] = []  # guard: _lock
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._restored = False  # snapshot loaded => this is a restart
        self._kv_soft_ts: Dict[Tuple[str, bytes], float] = {}  # guard: _lock
        # ---- failure detection / fencing state ----
        # node_id -> highest incarnation ever assigned.  PERSISTED (tiny,
        # monotonic counters): a GCS restart must not hand a resurrected
        # partitioned node its old incarnation back — fencing depends on
        # stale incarnations staying stale.  Node MEMBERSHIP stays soft.
        self._incarnations: Dict[str, int] = {}  # guard: _lock
        # node_id -> highest incarnation ever DECLARED DEAD.  Also
        # persisted: node membership is soft, so after a GCS restart a
        # healed zombie's heartbeat would otherwise look like a plain
        # "unknown node, please re-register" — it must instead learn it
        # was fenced, kill its stale workers, and only then come back.
        self._fenced_incs: Dict[str, int] = {}  # guard: _lock
        self._probing: set = set()  # nodes with an in-flight probe  # guard: _lock
        # token -> {"event": Event, "ok": bool} for indirect (peer-relayed)
        # probes; replies land via the probe_report op.
        self._probe_waiters: Dict[str, dict] = {}  # guard: _lock
        # drain lifecycle: node_id -> {state: draining|drained, started, stats}
        self._drains: Dict[str, dict] = {}  # guard: _lock
        # detection/fencing counters (surfaced by health_stats + metrics)
        self._m_suspects = 0        # guard: _lock — SUSPECT transitions
        self._m_false_suspects = 0  # guard: _lock — suspects that recovered
        self._m_fenced = 0          # guard: _lock — rejected stale frames
        self._m_deaths = 0          # guard: _lock — detected (non-drain) deaths
        self._m_probe_deaths = 0    # guard: _lock — deaths confirmed by probe
        self._m_ttd: deque = deque(maxlen=256)  # guard: _lock — detect latencies
        self._gm: Optional[dict] = None  # internal metric instruments
        if persist_path:
            self._load_snapshot()
            self._start_flusher()

    # ------------------------------------------------------- persistence

    def _mark_dirty(self):  # requires: _lock
        if self._persist_path:
            self._dirty = True

    def _load_snapshot(self):
        import pickle

        try:
            with open(self._persist_path, "rb") as f:
                snap = pickle.load(f)
        except (OSError, EOFError, pickle.UnpicklingError):
            return
        with self._lock:
            self._kv = snap.get("kv", {})
            self._functions = snap.get("functions", {})
            self._actors = snap.get("actors", {})
            self._named = snap.get("named", {})
            self._cluster_pgs = snap.get("cluster_pgs", {})
            self._incarnations = snap.get("incarnations", {})
            self._fenced_incs = snap.get("fenced_incarnations", {})
            # Actors whose host nodes are gone (nodes are soft state) are
            # surfaced as restarting; their home raylet reconciles on
            # reconnect.  start_restart_reconciler() handles the raylets
            # that never come back.
            for info in self._actors.values():
                if info.get("state") == "alive":
                    info["state"] = "restarting"
            # Incarnations count too: a cluster of pure task nodes has no
            # durable actors/kv, but its raylets still need ghost-death
            # declarations if they vanish during the outage.
            self._restored = bool(self._actors or self._kv
                                  or self._cluster_pgs
                                  or self._incarnations)

    def _write_snapshot(self):
        import pickle

        # One writer at a time: the periodic flusher and stop()'s final
        # flush share a tmp path; unserialized concurrent writes could
        # install interleaved garbage via os.replace.
        with self._flush_lock:
            # Shallow-copy the tables under the GCS lock (values are
            # bytes/small dicts), then pickle + write OUTSIDE it so a
            # multi-MB serialization never stalls heartbeats/scheduling.
            # _dirty clears AT COPY TIME: mutations racing the write
            # re-mark it and the next flush catches them; a FAILED write
            # re-sets it so acknowledged state is never silently dropped.
            with self._lock:
                tables = {
                    "kv": {k: v for k, v in self._kv.items()
                           if k[0] not in self._SOFT_KV_NS},
                    "functions": dict(self._functions),
                    "actors": {k: dict(v) for k, v in self._actors.items()},
                    "named": dict(self._named),
                    # copy the NESTED mutables too (assignments/bundles are
                    # mutated in place by PG repair): pickling outside the
                    # lock must never iterate a dict another thread edits
                    "cluster_pgs": {
                        k: {**v,
                            "bundles": [dict(b) for b in v["bundles"]],
                            "assignments": dict(v["assignments"]),
                            "pending": set(v["pending"])}
                        for k, v in self._cluster_pgs.items()},
                    "incarnations": dict(self._incarnations),
                    "fenced_incarnations": dict(self._fenced_incs),
                }
                self._dirty = False
            try:
                snap = pickle.dumps(tables, protocol=5)
                tmp = self._persist_path + f".tmp{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(snap)
                os.replace(tmp, self._persist_path)
            except BaseException:
                with self._lock:
                    self._dirty = True
                raise

    def _start_flusher(self):
        def loop():
            while not self._stop.wait(0.1):
                if self._dirty:  # unguarded-ok: racy flag read; rechecked under _flush_lock/_lock in _write_snapshot
                    try:
                        self._write_snapshot()
                    except Exception:  # noqa: BLE001 — flusher must live
                        traceback.print_exc()
            # unguarded-ok: shutdown-path flag read; a lost race means one
            # extra (idempotent) snapshot or a flush the NEXT start replays
            if self._dirty:  # final flush on shutdown
                try:
                    self._write_snapshot()
                except Exception:  # noqa: BLE001
                    traceback.print_exc()

        threading.Thread(target=loop, name="gcs-persist",
                         daemon=True).start()

    # ----------------------------------------------------------- pubsub

    def subscribe(self, callback: Callable[[str, Any], None],
                  node_id: Optional[str] = None):
        with self._lock:
            self._subs.append((node_id, callback))

    def unsubscribe(self, callback):
        with self._lock:
            self._subs = [(n, cb) for n, cb in self._subs if cb is not callback]

    def _publish(self, event: str, data: Any,
                 target_node: Optional[str] = None):
        with self._lock:
            subs = list(self._subs)
        for node_id, cb in subs:
            if target_node is not None and node_id != target_node:
                continue
            try:
                cb(event, data)
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    # ----------------------------------------------------------- nodes

    def register_node(self, node_id: str, address: Optional[tuple],
                      resources: Dict[str, float],
                      store_path: Optional[str] = None,
                      hostname: str = "",
                      labels: Optional[Dict[str, str]] = None,
                      data_port: Optional[int] = None,
                      incarnation: Optional[int] = None) -> List[dict]:
        """``labels`` carry scheduler-visible topology metadata (SURVEY §7
        items 3-4): ``accelerator_type`` (e.g. "v5e-8"), ``tpu_slice``
        (the pod-slice id — nodes sharing it are ICI-adjacent),
        ``tpu_topology`` ("2x4"), ``tpu_worker_id`` (coords within the
        slice).  STRICT_PACK placement uses ``tpu_slice`` to pack bundles
        across hosts of ONE slice when a single node can't hold them.

        ``incarnation``: the generation the raylet LAST HELD (0 for a
        fresh node).  The assigned value is ALWAYS strictly greater than
        both it and any value this GCS previously assigned for the
        node_id, so frames stamped with an older incarnation are
        rejectable after a death declaration — the fencing that makes a
        healed partition unable to double-execute (reference: raylet
        restarts bump the node's instance id).  The caller proposal
        matters when the GCS itself lost its counters (restart without
        persistence): without it the node would be re-assigned a number
        its peers have already fenced and be rejected by them forever.
        The caller reads its assigned incarnation back out of the
        returned snapshot."""
        with self._lock:
            inc = max(self._incarnations.get(node_id, 0),
                      int(incarnation or 0)) + 1
            self._incarnations[node_id] = inc
            if self._persist_path:
                self._mark_dirty()
            self._nodes[node_id] = {
                "node_id": node_id,
                "address": address,
                # data-plane listener (zero-copy object transfer); None for
                # nodes running without a data channel
                "data_port": data_port,
                "resources_total": dict(resources),
                "resources_available": dict(resources),
                "store_path": store_path,
                "hostname": hostname,
                "labels": dict(labels or {}),
                "alive": True,
                "suspect": False,
                "incarnation": inc,
                "last_heartbeat": time.monotonic(),
                # wall-clock registration stamp: lets chaos/soak tooling
                # verify that a mass reconnect after a GCS restart
                # re-registered STAGGERED (thundering-herd regression)
                "registered_at": time.time(),
            }
            snapshot = [dict(n) for n in self._nodes.values()]
        # Persist the incarnation bump SYNCHRONOUSLY (registrations are
        # rare): if the GCS dies before the async flusher runs, a restart
        # would re-assign the fenced number and peers would reject the
        # legitimately re-registered node forever.  A failed write re-marks
        # dirty; registration still proceeds (soft membership).
        if self._persist_path:
            try:
                self._write_snapshot()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        self._publish("node_added", {"node_id": node_id, "address": address,
                                     "incarnation": inc,
                                     "data_port": data_port})
        return snapshot

    def unregister_node(self, node_id: str):
        # announced departure, not a detected failure: keep it out of the
        # time-to-detect distribution
        self._mark_dead(node_id, "node drained", detected=False)

    def drain_node(self, node_id: str,
                   timeout_s: Optional[float] = None) -> bool:
        """Begin a GRACEFUL drain: placement skips the node immediately
        (draining flag) and the node's raylet is asked — via a targeted
        ``node_drain`` push — to migrate sole-copy store objects out,
        checkpoint-and-relocate checkpointable actors, and wait for
        running tasks up to ``timeout_s``, then report ``drain_complete``
        (reference: the autoscaler's DrainNode RPC before instance
        termination).  A drained node dies with ZERO reconstructions.
        Returns False for an unknown/dead node."""
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or not info["alive"]:
                return False
            info["draining"] = True
            self._drains[node_id] = {"state": "draining",
                                     "started": time.monotonic()}
        # BROADCAST, not targeted: the draining raylet starts its drain,
        # and every OTHER raylet marks the node draining so replication
        # pushes and locality forwarding stop landing fresh bytes/tasks on
        # a node that is about to retire (their candidate filters check
        # the flag; without the broadcast they would never learn it).
        self._publish("node_drain",
                      {"node_id": node_id,
                       "timeout_s": timeout_s or config.drain_timeout_s})
        return True

    def drain_complete(self, node_id: str, stats: Optional[dict] = None):
        """The draining raylet quiesced (or hit its deadline): record the
        outcome and retire the node through the normal death path — by
        now every sole-copy object has a surviving holder, so the death
        event triggers zero reconstructions."""
        with self._lock:
            entry = self._drains.setdefault(
                node_id, {"state": "draining", "started": time.monotonic()})
            entry["state"] = "drained"
            entry["elapsed_s"] = time.monotonic() - entry["started"]
            entry["stats"] = dict(stats or {})
        self._mark_dead(node_id, "node drained", detected=False)

    def drain_status(self, node_id: str) -> dict:
        with self._lock:
            entry = self._drains.get(node_id)
            if entry is None:
                return {"state": "unknown"}
            out = dict(entry)
            out.pop("started", None)
            return out

    def _fence_ok(self, node_id: str, incarnation: Optional[int]) -> bool:  # requires: _lock
        """Accept/reject a node-attributed mutating frame.  ``None`` means
        an unstamped caller (tests, pre-fencing components): accepted as
        before.  A stamped frame is rejected when the node is not alive or
        the stamp is older than the node's current incarnation — the
        split-brain guard: a node declared dead that keeps sending
        (partition healed, process resumed) cannot resurrect directory
        entries or re-assert actors until it re-registers fresh."""
        if incarnation is None:
            return True
        info = self._nodes.get(node_id)
        if (info is not None and info["alive"]
                and int(incarnation) >= info["incarnation"]):
            return True
        self._m_fenced += 1
        return False

    def heartbeat(self, node_id: str, resources_available: Dict[str, float],
                  queue_len: int = 0, pending_shapes=None,
                  incarnation: Optional[int] = None):
        """``pending_shapes`` is the node's unfulfilled resource demand:
        ``[(shape_dict, count), ...]`` for queued tasks that cannot run with
        current availability — the load signal the autoscaler bin-packs
        (reference: raylet resource reports aggregated by
        ``monitor.py:249`` ``update_load_metrics``).

        Returns True (accepted), False (unknown node — re-register), or
        the string ``"fenced"`` (this node_id+incarnation was declared
        dead: the raylet must kill its workers and re-register under a
        fresh incarnation before any of its frames are accepted again)."""
        recovered = None
        with self._lock:
            info = self._nodes.get(node_id)
            if incarnation is not None and \
                    int(incarnation) <= self._fenced_incs.get(node_id, -1):
                # declared dead under this (or an older) incarnation —
                # membership may be gone (GCS restart; nodes are soft
                # state) but the persisted fence record is not: the
                # zombie must kill its workers before re-registering
                self._m_fenced += 1
                return "fenced"
            if info is None:
                return False
            if incarnation is not None and (
                    not info["alive"]
                    or int(incarnation) < info["incarnation"]):
                self._m_fenced += 1
                return "fenced"
            if not info["alive"]:
                return False  # unstamped legacy caller: plain re-register
            info["resources_available"] = dict(resources_available)
            info["queue_len"] = queue_len
            info["pending_shapes"] = list(pending_shapes or ())
            now = time.monotonic()
            info["last_heartbeat"] = now
            if info.get("suspect"):
                # the node was only slow (GC pause, load): clear the
                # suspicion without any recovery action
                info["suspect"] = False
                self._m_false_suspects += 1
                recovered = info["incarnation"]
            busy = (queue_len > 0 or pending_shapes
                    or any(resources_available.get(k, 0.0) + 1e-9 < v
                           for k, v in info["resources_total"].items()))
            if busy:
                info.pop("idle_since", None)
            elif "idle_since" not in info:
                info["idle_since"] = now
        if recovered is not None:
            self._publish("node_suspect",
                          {"node_id": node_id, "suspect": False,
                           "incarnation": recovered})
        return True

    def load_metrics(self) -> List[dict]:
        """Autoscaler view: per-node capacity, availability, queue depth,
        unfulfilled demand shapes, and idle duration."""
        now = time.monotonic()
        with self._lock:
            out = []
            for info in self._nodes.values():
                out.append({
                    "node_id": info["node_id"],
                    "alive": info["alive"],
                    "suspect": bool(info.get("suspect")),
                    "draining": bool(info.get("draining")),
                    "hostname": info.get("hostname", ""),
                    "resources_total": dict(info["resources_total"]),
                    "resources_available": dict(
                        info.get("resources_available", {})),
                    "queue_len": info.get("queue_len", 0),
                    "pending_shapes": list(info.get("pending_shapes", ())),
                    "idle_s": (now - info["idle_since"]
                               if info.get("idle_since") is not None
                               and info["alive"] else 0.0),
                })
            return out

    def nodes(self) -> List[dict]:
        with self._lock:
            return [dict(n) for n in self._nodes.values()]

    def get_node(self, node_id: str) -> Optional[dict]:
        with self._lock:
            info = self._nodes.get(node_id)
            return dict(info) if info else None

    def _mark_dead(self, node_id: str, reason: str, detected: bool = True):
        """``detected``: this death was INFERRED (missed heartbeats /
        failed probe) rather than announced (drain, graceful shutdown) —
        only inferred deaths feed the time-to-detect distribution."""
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or not info["alive"]:
                return
            info["alive"] = False
            info["suspect"] = False
            info["death_reason"] = reason
            incarnation = info["incarnation"]
            self._fenced_incs[node_id] = max(
                self._fenced_incs.get(node_id, 0), incarnation)
            self._mark_dirty()  # the fence must survive a GCS restart
            if detected:
                self._m_deaths += 1
                self._m_ttd.append(
                    time.monotonic() - info["last_heartbeat"])
                if self._gm is not None:
                    self._gm["ttd"].observe(self._m_ttd[-1])
            # prune the directory: bytes on a dead node are gone.  Entries
            # with no holder left are DELETED, not kept with stale
            # metadata — their max()-accumulated size must not outlive the
            # last copy (a reconstruction may re-seal the object smaller,
            # and a stale larger size would drive out-of-range pull reads
            # that scrub valid holders).
            for oid, entry in list(self._objects.items()):
                entry["nodes"].discard(node_id)
                entry.get("replicas", set()).discard(node_id)
                if not entry["nodes"]:
                    del self._objects[oid]
        self._publish("node_dead", {"node_id": node_id, "reason": reason,
                                    "incarnation": incarnation})
        self._repair_pgs_for_dead_node(node_id)

    def _repair_pgs_for_dead_node(self, node_id: str):
        """Re-place cluster-PG bundles that lived on a dead node onto the
        remaining nodes (reference: GcsPlacementGroupManager reschedules
        bundles on node failure).  Un-placeable bundles drop out of the
        assignment table — tasks pinned to them defer until capacity
        appears rather than forwarding to a corpse."""
        with self._lock:
            pgs = list(self._cluster_pgs.items())
        for pg_id, entry in pgs:
            affected = sorted(i for i, n in entry["assignments"].items()
                              if n == node_id)
            if not affected:
                continue
            with self._lock:
                entry["pending"].discard(node_id)
                for i in affected:
                    del entry["assignments"][i]
                entry["state"] = "reserving"
                self._mark_dirty()
            sub_bundles = [entry["bundles"][i] for i in affected]
            placed = self._place_bundles(sub_bundles, entry["strategy"])
            if placed is None:
                continue  # keep un-assigned; retried on next node change
            with self._lock:
                for j, node in placed.items():
                    entry["assignments"][affected[j]] = node
                    entry["pending"].add(node)
                self._mark_dirty()
            for node in set(placed.values()):
                sub = {affected[j]: sub_bundles[j]
                       for j, n in placed.items() if n == node}
                self._publish("pg_reserve",
                              {"pg_id": pg_id, "bundles": sub},
                              target_node=node)

    def start_restart_reconciler(self, delay: Optional[float] = None):
        """Post-restart sweep for raylets that never reconnect.

        Snapshot-reloaded actors come back as 'restarting' on the theory
        that their home raylet will re-assert them — but a raylet that
        died DURING the GCS outage never re-registers and (the node table
        being soft state) never produces a node-death event either, so
        those actors would stay 'restarting' forever and named-actor
        callers would hang.  Once the reconnect window elapses: raylets
        that held a live incarnation in the snapshot but never returned
        are DECLARED DEAD (fence + node_dead publish — peers must fail
        forwarded work and reconstruct, exactly as for a probe-confirmed
        death; without this, an in-flight actor call to a node killed
        during the outage never resolves), actors
        whose owner node never returned go to 'dead' (lookups then raise
        instead of hanging), and cluster-PG bundles assigned to ghost
        nodes are re-placed through the normal dead-node repair path.  A
        slow-but-alive raylet that reconnects later simply re-asserts its
        actors back to 'alive' — the sweep is recoverable, not fatal."""
        if not self._restored:
            return
        if delay is None:
            delay = config.gcs_restart_reconcile_s

        def run():
            if self._stop.wait(delay):
                return
            with self._lock:
                live = {nid for nid, i in self._nodes.items() if i["alive"]}
                # Raylets that held a live incarnation at snapshot time
                # (above their fence watermark) and never re-registered
                # died DURING the outage — the suspicion machine never saw
                # them, so without an explicit declaration here no
                # node_dead is ever published and peers keep forwarding to
                # (and waiting on) a corpse: in-flight actor calls hang
                # instead of failing over.
                ghost_raylets = [
                    (nid, inc) for nid, inc in self._incarnations.items()
                    if nid not in live
                    and inc > self._fenced_incs.get(nid, -1)]
                ghost_actors = [
                    aid for aid, i in self._actors.items()
                    if i.get("state") in ("restarting", "pending")
                    and i.get("owner_node") not in live
                ]
                ghost_nodes = set()
                for entry in self._cluster_pgs.values():
                    ghost_nodes.update(
                        n for n in entry["assignments"].values()
                        if n not in live)
                    ghost_nodes.update(
                        n for n in entry["pending"] if n not in live)
            # Declare ghost raylets dead FIRST: the node_dead push is what
            # makes peers fail forwarded work (ActorDiedError), rotate
            # pulls, and reconstruct sole-copy objects.  A slow-but-alive
            # raylet declared here recovers like any probe-death false
            # positive: its next heartbeat returns "fenced", it kills its
            # stale workers, and re-registers under a fresh incarnation.
            for nid, inc in ghost_raylets:
                with self._lock:
                    info = self._nodes.get(nid)
                    if info is not None and info["alive"]:
                        continue  # reconnected since the sweep above
                    if inc <= self._fenced_incs.get(nid, -1):
                        continue
                    self._fenced_incs[nid] = inc
                    self._m_deaths += 1
                    self._mark_dirty()  # the fence must survive a restart
                self._publish("node_dead", {
                    "node_id": nid,
                    "reason": "raylet never reconnected after GCS restart",
                    "incarnation": inc})
            for aid in ghost_actors:
                with self._lock:
                    info = self._actors.get(aid)
                    # re-check: the owner may have reconnected since
                    if (info is None
                            or info.get("state") not in ("restarting",
                                                         "pending")
                            or info.get("owner_node") in {
                                nid for nid, i in self._nodes.items()
                                if i["alive"]}):
                        continue
                    info["state"] = "dead"
                    info["death_reason"] = (
                        "owner raylet never reconnected after GCS restart")
                    self._mark_dirty()
            for nid in ghost_nodes:
                self._repair_pgs_for_dead_node(nid)

        threading.Thread(target=run, name="gcs-restart-reconcile",
                         daemon=True).start()

    def start_health_monitor(self):
        if self._monitor is not None:
            return
        self._init_health_metrics()

        def loop():
            period = max(0.05, config.gcs_heartbeat_interval_s / 2)
            soft_sweep_at = time.monotonic() + self._SOFT_KV_TTL_S
            metrics_at = time.monotonic() + 1.0
            alerts_at = time.monotonic() + config.alerts_eval_interval_s
            while not self._stop.wait(period):
                timeout = config.gcs_node_timeout_s
                suspect_after = config.gcs_node_suspect_s
                probing = config.gcs_probe_enabled
                now = time.monotonic()
                stale, suspects = [], []
                with self._lock:
                    for nid, info in self._nodes.items():
                        if not info["alive"] or info["address"] is None:
                            continue
                        silent = now - info["last_heartbeat"]
                        if silent > timeout:
                            # hard fallback: probes never concluded (or
                            # probing is off) — plain heartbeat silence
                            stale.append(nid)
                        elif (probing and silent > suspect_after
                                and not info.get("suspect")):
                            info["suspect"] = True
                            self._m_suspects += 1
                            if self._gm is not None:
                                self._gm["suspects"].inc()
                            suspects.append((nid, info["incarnation"]))
                for nid in stale:
                    self._mark_dead(nid, "missed heartbeats")
                for nid, inc in suspects:
                    # a SUSPECT node is routed around but NOT recovered:
                    # reconstruction/replication repair only fires on DEAD
                    self._publish("node_suspect",
                                  {"node_id": nid, "suspect": True,
                                   "incarnation": inc})
                    self._start_probe(nid)
                if now >= metrics_at:
                    metrics_at = now + 1.0
                    self._flush_health_metrics()
                if now >= alerts_at:
                    alerts_at = now + max(
                        0.25, config.alerts_eval_interval_s)
                    try:
                        self._eval_alerts()
                    except Exception:  # noqa: BLE001 — rules never kill
                        pass           # the failure detector's thread
                if now >= soft_sweep_at:
                    # TTL sweep of soft KV (dead metric producers)
                    soft_sweep_at = now + self._SOFT_KV_TTL_S
                    with self._lock:
                        dead_keys = [
                            k for k, ts in self._kv_soft_ts.items()
                            if now - ts > self._SOFT_KV_TTL_S]
                        for k in dead_keys:
                            self._kv_soft_ts.pop(k, None)
                            self._kv.pop(k, None)

        self._monitor = threading.Thread(target=loop, name="gcs-health",
                                         daemon=True)
        self._monitor.start()

    # ------------------------------------------------- liveness probing

    def _start_probe(self, node_id: str):
        with self._lock:
            if node_id in self._probing:
                return
            self._probing.add(node_id)
        threading.Thread(target=self._probe_node, args=(node_id,),
                         name=f"gcs-probe-{node_id[:8]}",
                         daemon=True).start()

    def _probe_node(self, node_id: str):
        """Prober thread for ONE suspect node: a direct TCP ping, then —
        so a GCS<->node link blip can't kill a healthy node — one
        indirect ping relayed through a peer raylet.  Either success
        clears the suspicion; both failing confirms DEAD immediately
        (sub-second, vs waiting out gcs_node_timeout_s)."""
        try:
            with self._lock:
                info = self._nodes.get(node_id)
                if (info is None or not info["alive"]
                        or not info.get("suspect")):
                    return
                addr = info["address"]
                inc = info["incarnation"]
                hb = info["last_heartbeat"]
            ok = self._direct_probe(addr, node_id, inc)
            if not ok:
                ok = self._indirect_probe(node_id, addr, inc)
            publish_recovered = False
            with self._lock:
                info = self._nodes.get(node_id)
                if info is None or not info["alive"]:
                    return
                if info["last_heartbeat"] > hb or not info.get("suspect"):
                    return  # a heartbeat raced the probe: already settled
                if ok:
                    info["suspect"] = False
                    # defer the next suspicion cycle: the node answered a
                    # ping NOW, so treat the probe as a liveness proof even
                    # though heartbeats are still in flight
                    info["last_heartbeat"] = time.monotonic()
                    self._m_false_suspects += 1
                    publish_recovered = True
                else:
                    self._m_probe_deaths += 1
            if publish_recovered:
                self._publish("node_suspect",
                              {"node_id": node_id, "suspect": False,
                               "incarnation": inc})
            elif not ok:
                self._mark_dead(node_id,
                                "liveness probe failed after missed "
                                "heartbeats")
        finally:
            with self._lock:
                self._probing.discard(node_id)

    def _direct_probe(self, address, node_id: str, incarnation: int) -> bool:
        return protocol.liveness_ping(address, node_id, incarnation,
                                      config.gcs_probe_timeout_s)

    def _indirect_probe(self, target: str, address, incarnation: int) -> bool:
        """Ask one healthy peer raylet to ping the target and report back
        (probe_report op).  Covers the asymmetric-partition case where the
        GCS can't reach a node its peers still can."""
        with self._lock:
            helpers = [
                nid for nid, info in self._nodes.items()
                if info["alive"] and not info.get("suspect")
                and nid != target and info["address"] is not None
            ]
            if not helpers:
                return False
            helper = random.choice(helpers)
            token = f"{target}:{incarnation}:{self._m_suspects}"
            waiter = {"event": threading.Event(), "ok": False}
            self._probe_waiters[token] = waiter
        self._publish("node_probe",
                      {"target": target, "address": tuple(address),
                       "incarnation": incarnation, "token": token},
                      target_node=helper)
        waiter["event"].wait(max(0.05, config.gcs_probe_timeout_s) * 2)
        with self._lock:
            self._probe_waiters.pop(token, None)
        return waiter["ok"]

    def probe_report(self, token: str, ok: bool):
        """Indirect-probe verdict from the helper raylet."""
        with self._lock:
            waiter = self._probe_waiters.get(token)
        if waiter is not None:
            waiter["ok"] = bool(ok)
            waiter["event"].set()

    def health_stats(self) -> dict:
        """Failure-detection observability: suspicion / fencing counters
        and the recent time-to-detect distribution (also exported as
        ray_tpu_internal_* series into the metrics KV)."""
        with self._lock:
            ttd = sorted(self._m_ttd)
            return {
                "suspects_total": self._m_suspects,
                "false_suspects_total": self._m_false_suspects,
                "fenced_frames_total": self._m_fenced,
                "deaths_detected_total": self._m_deaths,
                "probe_confirmed_deaths_total": self._m_probe_deaths,
                "time_to_detect_s": ttd,
                "time_to_detect_p50_s":
                    ttd[len(ttd) // 2] if ttd else None,
                "drains": {nid: {k: v for k, v in d.items()
                                 if k != "started"}
                           for nid, d in self._drains.items()},
            }

    def _init_health_metrics(self):
        """GCS-side ray_tpu_internal_* series, flushed straight into this
        core's OWN metrics KV namespace (the GCS has no worker/raylet
        flusher of its own; the dashboard's /metrics merges producers)."""
        try:
            from ray_tpu.util import metrics as _metrics

            tags = {"node": "gcs"}
            self._gm = {
                "suspects": _metrics.internal_metric(
                    _metrics.Counter, "ray_tpu_internal_node_suspects_total",
                    "Nodes marked SUSPECT after missed heartbeats",
                    tag_keys=("node",)).set_default_tags(tags),
                "fenced": _metrics.internal_metric(
                    _metrics.Counter, "ray_tpu_internal_fenced_frames_total",
                    "Stale node-attributed frames rejected by incarnation "
                    "fencing", tag_keys=("node",)).set_default_tags(tags),
                "ttd": _metrics.internal_metric(
                    _metrics.Histogram, "ray_tpu_internal_time_to_detect_s",
                    "Last-contact to death-declaration latency for "
                    "detected node failures",
                    boundaries=(0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 10.0),
                    tag_keys=("node",)).set_default_tags(tags),
                "false_suspects": _metrics.internal_metric(
                    _metrics.Counter,
                    "ray_tpu_internal_false_suspects_total",
                    "SUSPECT nodes that recovered (probe or heartbeat "
                    "cleared the suspicion)",
                    tag_keys=("node",)).set_default_tags(tags),
                "deaths": _metrics.internal_metric(
                    _metrics.Counter,
                    "ray_tpu_internal_node_deaths_detected_total",
                    "Node deaths INFERRED from silence/probes (announced "
                    "drain deaths excluded)",
                    tag_keys=("node",)).set_default_tags(tags),
                "probe_deaths": _metrics.internal_metric(
                    _metrics.Counter,
                    "ray_tpu_internal_probe_confirmed_deaths_total",
                    "Node deaths confirmed sub-second by a failed "
                    "direct+indirect probe pair",
                    tag_keys=("node",)).set_default_tags(tags),
                "drains": _metrics.internal_metric(
                    _metrics.Gauge, "ray_tpu_internal_node_drains",
                    "Nodes with a recorded drain lifecycle (draining or "
                    "drained)", tag_keys=("node",)).set_default_tags(tags),
                "alerts_firing": _metrics.internal_metric(
                    _metrics.Gauge, "ray_tpu_internal_alerts_firing",
                    "Alert rules currently in the firing state",
                    tag_keys=("node",)).set_default_tags(tags),
            }
            # delta-sync baselines: the _m_* counters are bumped inline
            # under _lock; the flusher ships increments into the Counter
            # instruments so restarts/re-inits never double-count
            self._gm_last = {"fenced": 0, "false_suspects": 0, "deaths": 0,
                             "probe_deaths": 0}
            # time-series baselines for collect_points (flusher thread only)
            self._gm_points_last = {}
        except Exception:  # noqa: BLE001 — stats-only fallback
            self._gm = None

    def _flush_health_metrics(self):
        if self._gm is None:
            return
        import json as _json

        with self._lock:
            current = {"fenced": self._m_fenced,
                       "false_suspects": self._m_false_suspects,
                       "deaths": self._m_deaths,
                       "probe_deaths": self._m_probe_deaths}
            drains = len(self._drains)
        for key, value in current.items():
            delta = value - self._gm_last[key]
            if delta > 0:
                self._gm[key].inc(delta)
            self._gm_last[key] = value
        self._gm["drains"].set(drains)
        items = []
        for m in self._gm.values():
            try:
                payload = m._export()
            except Exception:  # noqa: BLE001
                continue
            if payload is None:
                continue
            items.append((f"gcs-{os.getpid()}/{m.name}".encode(),
                          _json.dumps(payload).encode()))
        if items:
            self.kv_multi_put("metrics", items)
        if config.metrics_history:
            # the GCS's own series feed the time-series table too — the
            # alert engine's detector rules read them (false suspects,
            # fenced frames) like any other producer's
            from ray_tpu.util import metrics as _metrics

            points = _metrics.collect_points(self._gm.values(),
                                             self._gm_points_last)
            if points:
                self.add_metric_points("gcs", points)

    def stop(self):
        self._stop.set()
        # Synchronous final flush: a graceful shutdown must not lose
        # acknowledged durable mutations to the async-flusher window.
        if self._persist_path and self._dirty:  # unguarded-ok: shutdown-path flag read
            try:
                self._write_snapshot()
            except OSError:
                pass

    # ----------------------------------------------------------- placement

    def place_task(self, resources: Dict[str, float],
                   exclude: Optional[List[str]] = None,
                   arg_ids: Optional[List[str]] = None) -> Optional[str]:
        """Pick an alive node whose AVAILABLE resources fit — most
        argument bytes already local first (``arg_ids``: the task's
        dependency object ids, scored against the object directory —
        reference: locality-aware leasing), then most-available (a
        spread-flavoured policy; the reference's hybrid policy packs to
        50% then spreads, `scheduling/policy/hybrid_scheduling_policy.h:50`).
        Returns None when nothing fits right now."""
        exclude = set(exclude or ())
        best, best_score = None, None
        with self._lock:
            loc_bytes: Dict[str, int] = {}
            for oid in arg_ids or ():
                entry = self._objects.get(oid)
                if entry:
                    for nid in entry["nodes"]:
                        loc_bytes[nid] = loc_bytes.get(nid, 0) \
                            + (entry["size"] or 0)
            for nid, info in self._nodes.items():
                if not info["alive"] or nid in exclude \
                        or info.get("draining") or info.get("suspect"):
                    continue
                avail = info["resources_available"]
                if all(avail.get(k, 0.0) + 1e-9 >= v
                       for k, v in resources.items()):
                    score = (loc_bytes.get(nid, 0),
                             sum(avail.values()) - len(resources))
                    if best_score is None or score > best_score:
                        best, best_score = nid, score
        return best

    def feasible_nodes(self, resources: Dict[str, float]) -> List[str]:
        """Nodes whose TOTAL capacity fits (for infeasibility diagnosis)."""
        with self._lock:
            return [
                nid for nid, info in self._nodes.items()
                if info["alive"] and not info.get("draining") and all(
                    info["resources_total"].get(k, 0.0) + 1e-9 >= v
                    for k, v in resources.items())
            ]

    # ----------------------------------------------------------- cluster PGs

    def create_pg(self, pg_id: str, bundles: List[Dict[str, float]],
                  strategy: str, origin_node: str) -> bool:
        """Place each bundle on a node per the strategy and ask the
        involved raylets (pg_reserve push) to reserve their fragments
        (reference: GcsPlacementGroupScheduler + the 2PC bundle
        reservation, `placement_group_resource_manager.cc`).  False =
        infeasible against current cluster TOTALS."""
        assignments = self._place_bundles(bundles, strategy)
        if assignments is None:
            return False
        with self._lock:
            self._cluster_pgs[pg_id] = {
                "bundles": bundles,
                "strategy": strategy,
                "assignments": assignments,
                "origin": origin_node,
                "pending": set(assignments.values()),
                "state": "reserving",
            }
            self._mark_dirty()
        for node in set(assignments.values()):
            sub = {i: bundles[i] for i, n in assignments.items()
                   if n == node}
            self._publish("pg_reserve",
                          {"pg_id": pg_id, "bundles": sub},
                          target_node=node)
        return True

    def _place_bundles(self, bundles, strategy):
        """Greedy placement against the latest heartbeat availability;
        falls back to capacity totals so a currently-busy cluster still
        places (fragments then pend locally until resources free)."""
        def placeable(info) -> bool:
            return (info["alive"] and not info.get("draining")
                    and not info.get("suspect"))

        with self._lock:
            nodes = {nid: dict(info["resources_available"])
                     for nid, info in self._nodes.items() if placeable(info)}
            totals = {nid: dict(info["resources_total"])
                      for nid, info in self._nodes.items() if placeable(info)}
            slices = {nid: info.get("labels", {}).get("tpu_slice")
                      for nid, info in self._nodes.items()
                      if placeable(info)}
        if not nodes:
            return None

        def fits(avail, b):
            return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in b.items())

        def take(avail, b):
            for k, v in b.items():
                avail[k] = avail.get(k, 0.0) - v

        def pack_into(pool, nids):
            """All bundles greedily into the given node set, or None."""
            trial = {nid: dict(pool[nid]) for nid in nids if nid in pool}
            out: Dict[int, str] = {}
            for i, b in enumerate(bundles):
                nid = next((n for n in trial if fits(trial[n], b)), None)
                if nid is None:
                    return None
                take(trial[nid], b)
                out[i] = nid
            return out

        assignments: Dict[int, str] = {}
        if strategy in ("STRICT_PACK", "PACK"):
            # one node for everything when possible
            for pool in (nodes, totals):
                for nid in pool:
                    got = pack_into(pool, [nid])
                    if got is not None:
                        return got
            # TPU extension (SURVEY §7 items 3-4): a bundle set too big for
            # one host still packs onto ONE ICI domain — all hosts sharing
            # a tpu_slice label are directly connected, so same-slice
            # multi-host placement preserves STRICT_PACK's locality intent
            # where plain Ray would just fail.
            slice_groups: Dict[str, List[str]] = {}
            for nid, sl in slices.items():
                if sl:
                    slice_groups.setdefault(sl, []).append(nid)
            for pool in (nodes, totals):
                for sl, nids in sorted(slice_groups.items()):
                    got = pack_into(pool, sorted(nids))
                    if got is not None:
                        return got
            if strategy == "STRICT_PACK":
                return None
        if strategy == "STRICT_SPREAD":
            used: set = set()
            for i, b in enumerate(bundles):
                cand = next(
                    (nid for nid in totals
                     if nid not in used and fits(totals[nid], b)), None)
                if cand is None:
                    return None
                assignments[i] = cand
                used.add(cand)
            return assignments
        # PACK overflow / SPREAD: greedy, SPREAD rotates nodes.  The
        # capacity fallback tracks CUMULATIVE placements per node — a node
        # must fit everything assigned to it even if bundles will pend
        # until running work frees resources.
        order = sorted(totals)
        trem = {nid: dict(t) for nid, t in totals.items()}
        rr = 0
        # first-fit-decreasing: big bundles place first so small ones
        # don't squat on the only node the big one fits
        by_size = sorted(range(len(bundles)),
                         key=lambda i: -sum(bundles[i].values()))
        for i in by_size:
            b = bundles[i]
            placed = None
            for attempt in range(len(order)):
                nid = order[(rr + attempt) % len(order)]
                if fits(nodes[nid], b) and fits(trem[nid], b):
                    placed = nid
                    break
            if placed is None:
                placed = next(
                    (nid for nid in order if fits(trem[nid], b)), None)
                if placed is None:
                    return None
            take(nodes[placed], b)
            take(trem[placed], b)
            assignments[i] = placed
            if strategy == "SPREAD":
                rr = (order.index(placed) + 1) % len(order)
        return assignments

    def pg_fragment_ready(self, pg_id: str, node_id: str):
        with self._lock:
            entry = self._cluster_pgs.get(pg_id)
            if entry is None:
                return
            entry["pending"].discard(node_id)
            done = not entry["pending"]
            if done:
                entry["state"] = "created"
            origin = entry["origin"]
            self._mark_dirty()
        if done:
            self._publish("pg_ready", {"pg_id": pg_id}, target_node=origin)

    def remove_cluster_pg(self, pg_id: str):
        with self._lock:
            entry = self._cluster_pgs.pop(pg_id, None)
            if entry is not None:
                self._mark_dirty()
        if entry is None:
            return False
        for node in set(entry["assignments"].values()):
            self._publish("pg_remove", {"pg_id": pg_id}, target_node=node)
        if entry["origin"] not in set(entry["assignments"].values()):
            self._publish("pg_remove", {"pg_id": pg_id},
                          target_node=entry["origin"])
        return True

    def pg_info(self, pg_id: str) -> Optional[dict]:
        with self._lock:
            entry = self._cluster_pgs.get(pg_id)
            if entry is None:
                return None
            return {"assignments": dict(entry["assignments"]),
                    "bundles": list(entry["bundles"]),
                    "state": entry["state"], "origin": entry["origin"]}

    # ----------------------------------------------------------- kv

    # Soft-state KV namespaces: high-churn, rebuildable data (per-producer
    # metric samples flush ~1/s forever) — excluded from the durable
    # snapshot (else every flush rewrites it) and TTL-swept so dead
    # producers' keys don't accumulate.  Job logs stay durable by design
    # (documented: they outlive client and driver) — they are per-job
    # bounded, not per-second unbounded.
    _SOFT_KV_NS = frozenset({"metrics"})
    _SOFT_KV_TTL_S = 120.0

    def kv_put(self, ns: str, key: bytes, val: bytes):
        with self._lock:
            self._kv[(ns, key)] = val
            if ns in self._SOFT_KV_NS:
                self._kv_soft_ts[(ns, key)] = time.monotonic()
            else:
                self._mark_dirty()

    def kv_multi_put(self, ns: str, items):
        """Batched kv_put: one RPC/post for N keys of one namespace (the
        raylets' internal-metrics flush ships ~30 keys per interval —
        per-key posts were 30x the control-plane frames for the same
        data)."""
        with self._lock:
            now = time.monotonic()
            soft = ns in self._SOFT_KV_NS
            for key, val in items:
                self._kv[(ns, key)] = val
                if soft:
                    self._kv_soft_ts[(ns, key)] = now
            if items and not soft:
                self._mark_dirty()

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._kv.get((ns, key))

    def kv_del(self, ns: str, key: bytes) -> bool:
        with self._lock:
            existed = self._kv.pop((ns, key), None) is not None
            self._kv_soft_ts.pop((ns, key), None)
            if existed and ns not in self._SOFT_KV_NS:
                self._mark_dirty()
            return existed

    def kv_keys(self, ns: str, prefix: bytes) -> List[bytes]:
        with self._lock:
            return [k for (n, k) in self._kv
                    if n == ns and k.startswith(prefix)]

    # ----------------------------------------------------------- functions

    def put_function(self, fid: bytes, blob: bytes):
        with self._lock:
            self._functions[fid] = blob
            self._mark_dirty()

    def get_function(self, fid: bytes) -> Optional[bytes]:
        with self._lock:
            return self._functions.get(fid)

    # ----------------------------------------------------------- actors

    def register_actor(self, actor_id: bytes, owner_node: str,
                       name: Optional[str] = None, namespace: str = "",
                       spec_blob: Optional[bytes] = None,
                       incarnation: Optional[int] = None) -> bool:
        """False when the (namespace, name) is already taken — or when the
        registering node is fenced (a resurrected partitioned node must
        not re-assert actors the cluster already restarted elsewhere)."""
        with self._lock:
            if not self._fence_ok(owner_node, incarnation):
                return False
            if name:
                existing = self._named.get((namespace, name))
                if existing is not None and existing != actor_id:
                    return False  # name collision
            self._actors[actor_id] = {
                "owner_node": owner_node,
                "state": "pending",
                "name": name,
                "namespace": namespace,
                "spec_blob": spec_blob,
            }
            if name:
                self._named[(namespace, name)] = actor_id
            self._mark_dirty()
            return True

    def update_actor(self, actor_id: bytes, state: str,
                     node_id: Optional[str] = None,
                     checkpoint: Optional[str] = None,
                     checkpoint_seq: Optional[int] = None):
        """``checkpoint``/``checkpoint_seq``: latest checkpoint object id
        (hex) + sequence number of a checkpointable actor — the actor
        table tracks the freshest snapshot so state tooling can see what
        a restart would restore from."""
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None:
                return
            info["state"] = state
            if node_id is not None:
                info["exec_node"] = node_id
            if checkpoint is not None:
                info["checkpoint"] = checkpoint
                info["checkpoint_seq"] = checkpoint_seq or 0
            self._mark_dirty()

    def remove_actor(self, actor_id: bytes):
        with self._lock:
            info = self._actors.pop(actor_id, None)
            if info and info.get("name"):
                key = (info["namespace"], info["name"])
                if self._named.get(key) == actor_id:
                    del self._named[key]
            if info is not None:
                self._mark_dirty()

    def get_actor(self, actor_id: bytes) -> Optional[dict]:
        with self._lock:
            info = self._actors.get(actor_id)
            return dict(info) if info else None

    def lookup_named_actor(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            aid = self._named.get((namespace, name))
            if aid is None:
                return None
            info = dict(self._actors[aid])
            info["actor_id"] = aid
            return info

    def list_actors(self) -> List[dict]:
        with self._lock:
            return [{"actor_id": aid.hex() if isinstance(aid, bytes) else aid,
                     **{k: v for k, v in info.items() if k != "spec_blob"}}
                    for aid, info in self._actors.items()]

    # ----------------------------------------------------------- objects

    def add_object_location(self, oid: str, node_id: str, size: int = 0,
                            inline: bool = False, replica: bool = False,
                            incarnation: Optional[int] = None):
        """``replica``: this holder is an eager secondary copy (pushed by
        the sealing raylet for availability, not pulled by a consumer) —
        recorded so re-replication math can tell managed copies from
        incidental consumer-side caches.  Striping treats all holders the
        same, so every replica also doubles a pull's read bandwidth.

        ``incarnation``: the registering node's stamp — a fenced (dead or
        stale-incarnation) node cannot resurrect directory entries."""
        with self._lock:
            if not self._fence_ok(node_id, incarnation):
                return
            entry = self._objects.setdefault(
                oid, {"nodes": set(), "size": size, "inline": inline,
                      "replicas": set()})
            entry["nodes"].add(node_id)
            entry["size"] = max(entry["size"], size)
            entry["inline"] = entry["inline"] or inline
            if replica:
                entry.setdefault("replicas", set()).add(node_id)
            push_size, push_inline = entry["size"], entry["inline"]
            watchers = self._object_watchers.pop(oid, set())
        for w in watchers:
            self._publish("object_at",
                          {"oid": oid, "node_id": node_id,
                           "size": push_size, "inline": push_inline},
                          target_node=w)

    def remove_object_location(self, oid: str, node_id: Optional[str] = None):
        with self._lock:
            if node_id is None:
                self._objects.pop(oid, None)
                return
            entry = self._objects.get(oid)
            if entry:
                entry["nodes"].discard(node_id)
                entry.get("replicas", set()).discard(node_id)
                if not entry["nodes"]:
                    del self._objects[oid]

    def get_object_locations(self, oid: str,
                             watcher: Optional[str] = None) -> dict:
        """When no location is known and ``watcher`` is given, the watcher
        node gets an ``object_at`` push once somebody registers one
        (reference: object directory subscriptions,
        `ownership_based_object_directory.h`)."""
        with self._lock:
            entry = self._objects.get(oid)
            if entry and entry["nodes"]:
                return {"nodes": sorted(entry["nodes"]),
                        "size": entry["size"], "inline": entry["inline"],
                        "replicas": sorted(entry.get("replicas", ()))}
            if watcher is not None:
                self._object_watchers.setdefault(oid, set()).add(watcher)
            return {"nodes": [], "size": 0, "inline": False}

    def get_object_locations_batch(self, oids: List[str]) -> Dict[str, dict]:
        """One round trip for many objects (node-death recovery scans a
        dead node's whole holding set — per-object RPCs would serialize a
        raylet's event thread on GCS latency).  Objects with no known
        holder are simply absent from the result; no watches are
        registered."""
        with self._lock:
            out: Dict[str, dict] = {}
            for oid in oids:
                entry = self._objects.get(oid)
                if entry and entry["nodes"]:
                    out[oid] = {
                        "nodes": sorted(entry["nodes"]),
                        "size": entry["size"], "inline": entry["inline"],
                        "replicas": sorted(entry.get("replicas", ()))}
            return out

    # ----------------------------------------------------------- task events

    def add_task_events(self, node_id: str, events: List[dict],
                        dropped: int = 0,
                        incarnation: Optional[int] = None):
        """Batch append from one raylet's export ring buffer.  ``dropped``
        is how many events that raylet shed to backpressure since its last
        flush (the buffer never blocks dispatch — it drops and counts).
        Stamped batches from a fenced node are rejected whole (stale task
        completions must not overwrite the retried attempts' states)."""
        cap = max(1, config.task_events_max_per_job)
        with self._lock:
            if not self._fence_ok(node_id, incarnation):
                return
            self._task_events_dropped += dropped
            last_job, tasks, log = None, None, None
            for ev in events:
                job = ev.get("job_id") or "driver"
                if job != last_job:  # batches are almost always one job
                    slot = self._task_events.get(job)
                    if slot is None:
                        slot = {"events": deque(maxlen=cap), "tasks": {}}
                        self._task_events[job] = slot
                    last_job, tasks, log = job, slot["tasks"], slot["events"]
                log.append(ev)
                # pop+reinsert keeps dict order least-recently-updated
                # first, so cap overflow evicts stale finished tasks
                tid = ev["task_id"]
                tasks.pop(tid, None)
                tasks[tid] = ev
                if len(tasks) > cap:
                    tasks.pop(next(iter(tasks)))

    def _job_slots(self, job_id: Optional[str]) -> List[dict]:  # requires: _lock
        if job_id is not None:
            slot = self._task_events.get(job_id)
            return [slot] if slot else []
        return list(self._task_events.values())

    def list_task_events(self, job_id: Optional[str] = None,
                         state: Optional[str] = None,
                         limit: int = 1000) -> List[dict]:
        """Latest known state per task, cluster-wide (newest-updated
        first).  ``limit`` applies at the source."""
        with self._lock:
            rows: List[dict] = []
            for slot in self._job_slots(job_id):
                rows.extend(slot["tasks"].values())
        rows.sort(key=lambda ev: ev.get("time", 0.0), reverse=True)
        if state is not None:
            state = state.upper()
            rows = [ev for ev in rows if ev.get("state") == state]
        return rows[:max(0, limit)]

    def task_events_raw(self, job_id: Optional[str] = None,
                        limit: int = 100000) -> List[dict]:
        """The raw event log (every state transition) — timeline feed."""
        if limit <= 0:
            return []
        with self._lock:
            rows = []
            for slot in self._job_slots(job_id):
                rows.extend(slot["events"])
        rows.sort(key=lambda ev: ev.get("time", 0.0))
        return rows[-limit:]

    def summarize_task_events(self, job_id: Optional[str] = None) -> dict:
        """State -> count over the latest per-task states, plus export-drop
        and node-coverage accounting."""
        by_state: Dict[str, int] = {}
        nodes = set()
        num_tasks = 0
        with self._lock:
            for slot in self._job_slots(job_id):
                for ev in slot["tasks"].values():
                    num_tasks += 1
                    st = ev.get("state", "?")
                    by_state[st] = by_state.get(st, 0) + 1
                    if ev.get("node_id"):
                        nodes.add(ev["node_id"])
            dropped = self._task_events_dropped
        return {"by_state": by_state, "num_tasks": num_tasks,
                "num_dropped": dropped, "nodes": sorted(nodes)}

    # -------------------------------------------------------- trace table

    def add_trace_spans(self, node_id: str, spans: List[dict],
                        dropped: int = 0,
                        incarnation: Optional[int] = None):
        """Batch append from one process's span export buffer.  ``dropped``
        counts spans that producer shed to backpressure since its last
        flush.  Like task events, batches from a fenced node are rejected
        whole (a resurrected node must not rewrite request history)."""
        cap = max(1, config.trace_table_max)
        with self._lock:
            if not self._fence_ok(node_id, incarnation):
                return
            self._trace_dropped += dropped
            last_job, log = None, None
            for sp in spans:
                job = sp.get("job") or "driver"
                if job != last_job:  # batches are almost always one job
                    log = self._trace_spans.get(job)
                    if log is None:
                        log = self._trace_spans[job] = deque(maxlen=cap)
                    last_job = job
                if len(log) == cap:
                    self._trace_dropped += 1  # eviction, counted
                log.append(sp)

    def get_trace(self, trace_id: str) -> List[dict]:
        """Every retained span of one trace, cluster-wide (the flat
        record list — ``util.trace_analysis`` turns it into a tree /
        waterfall)."""
        with self._lock:
            return [sp for log in self._trace_spans.values()
                    for sp in log if sp.get("trace_id") == trace_id]

    def list_trace_spans(self, job_id: Optional[str] = None,
                         limit: int = 10000) -> List[dict]:
        """The most recent retained spans (newest last) — feed for the
        aggregate "where do the microseconds go" breakdown."""
        if limit <= 0:
            return []
        with self._lock:
            if job_id is not None:
                logs = [self._trace_spans.get(job_id) or ()]
            else:
                logs = list(self._trace_spans.values())
            rows = [sp for log in logs for sp in log]
        rows.sort(key=lambda sp: sp.get("start_us", 0))
        return rows[-limit:]

    def trace_table_stats(self) -> dict:
        with self._lock:
            num = sum(len(v) for v in self._trace_spans.values())
            traces = {sp.get("trace_id")
                      for log in self._trace_spans.values() for sp in log}
            return {"num_spans": num, "num_traces": len(traces),
                    "num_dropped": self._trace_dropped,
                    "jobs": sorted(self._trace_spans)}

    # ----------------------------------------------------- profile table

    def add_profile_samples(self, node_id: str, samples: List[dict],
                            dropped: int = 0,
                            incarnation: Optional[int] = None):
        """Batch append from one node's folded stack-sample buffers
        (every process on the node funnels through its raylet; the
        standalone GCS feeds its own samples under the "gcs" key).
        ``dropped`` counts records the producer shed to backpressure.
        Stamped batches from a fenced node are rejected whole."""
        cap = max(1, config.profile_table_max)
        with self._lock:
            if not self._fence_ok(node_id, incarnation):
                return
            self._profile_dropped += dropped
            log = self._profile_samples.get(node_id)
            if log is None:
                log = self._profile_samples[node_id] = deque(maxlen=cap)
            for rec in samples:
                if len(log) == cap:
                    self._profile_dropped += 1  # eviction, counted
                log.append(rec)

    def list_profile_samples(self, node_id: Optional[str] = None,
                             since: float = 0.0,
                             limit: int = 100000) -> List[dict]:
        """Retained folded sample records, cluster-wide or for one node
        (id prefix accepted); ``since`` keeps only records whose window
        ends at/after it — the timed-capture filter behind
        ``state.profile(duration_s)``."""
        if limit <= 0:
            return []
        with self._lock:
            if node_id is not None:
                logs = [log for nid, log in self._profile_samples.items()
                        if nid.startswith(node_id)]
            else:
                logs = list(self._profile_samples.values())
            rows = [rec for log in logs for rec in log
                    if rec.get("t1", 0.0) >= since]
        rows.sort(key=lambda rec: rec.get("t0", 0.0))
        return rows[-limit:]

    def profile_table_stats(self) -> dict:
        with self._lock:
            num = sum(len(v) for v in self._profile_samples.values())
            total = sum(int(rec.get("count", 0))
                        for log in self._profile_samples.values()
                        for rec in log)
            return {"num_records": num, "num_samples": total,
                    "num_dropped": self._profile_dropped,
                    "nodes": sorted(self._profile_samples)}

    # ------------------------------------------- metrics time-series table

    def add_metric_points(self, node_id: str, points: List[dict],
                          dropped: int = 0,
                          incarnation: Optional[int] = None):
        """Batch append from one node's metric point ring (every process
        on the node funnels through its raylet; the standalone GCS feeds
        its own points under the "gcs" key).  Points are DELTAS — counter
        and histogram-bucket increments per flush interval, gauge value
        changes — so merging across producer restarts needs no reset
        heuristics.  ``dropped`` counts points the producer's ring shed.
        Stamped batches from a fenced node are rejected whole."""
        cap = max(1, config.metrics_table_max)
        with self._lock:
            if not self._fence_ok(node_id, incarnation):
                return
            self._metric_points_dropped += dropped
            log = self._metric_points.get(node_id)
            if log is None:
                log = self._metric_points[node_id] = deque(maxlen=cap)
            for p in points:
                p["node"] = node_id
                if len(log) == cap:
                    self._metric_points_dropped += 1  # eviction, counted
                log.append(p)

    def _metric_points_snapshot(self, name: Optional[str] = None,
                                node_id: Optional[str] = None) -> List[dict]:
        """Flat point list (records are append-only after ingest, so
        handing out references is safe)."""
        with self._lock:
            if node_id is not None:
                logs = [log for nid, log in self._metric_points.items()
                        if nid.startswith(node_id)]
            else:
                logs = list(self._metric_points.values())
            if name is not None:
                return [p for log in logs for p in log
                        if p["name"] == name]
            return [p for log in logs for p in log]

    def query_metrics(self, name: Optional[str] = None, op: str = "range",
                      tags: Optional[Dict[str, str]] = None,
                      node_id: Optional[str] = None,
                      since: Optional[float] = None,
                      until: Optional[float] = None,
                      window_s: float = 60.0, q: float = 0.99,
                      limit: int = 2000) -> dict:
        """Query the time-series table.  ``op``:

        * ``range`` — the matching points themselves (newest ``limit``).
        * ``rate`` — per-second increase over the trailing ``window_s``.
        * ``quantile`` — histogram quantile ``q`` over the window (bucket
          deltas merged first — never averaged percentiles).
        * ``series`` — per-(name, tags) activity summary, the feed for
          ``ray_tpu metrics top``.

        The math lives in ``util.metrics_query`` (pure, shared with the
        alert engine)."""
        from ray_tpu.util import metrics_query as mq

        pts = mq.filter_points(
            self._metric_points_snapshot(name, node_id),
            name, tags, since, until)
        if op == "range":
            return {"op": "range", "count": len(pts),
                    "truncated": len(pts) > limit,
                    "points": pts[-max(0, limit):]}
        now = until
        if now is None:
            now = max((p["ts"] for p in pts), default=time.time())
        if op == "rate":
            return {"op": "rate", "window_s": window_s,
                    "points": len(pts),
                    "rate": mq.rate(pts, window_s, now=now)}
        if op == "quantile":
            return {"op": "quantile", "q": q, "window_s": window_s,
                    "points": len(pts),
                    "value": mq.quantile_over_window(pts, q, window_s,
                                                     now=now)}
        if op == "series":
            return {"op": "series",
                    "series": mq.series_summary(pts, window_s, now=now)}
        raise ValueError(f"unknown query op {op!r}")

    def metrics_table_stats(self) -> dict:
        with self._lock:
            num = sum(len(v) for v in self._metric_points.values())
            series = {(p["name"], tuple(map(tuple, p.get("tags", ()))))
                      for log in self._metric_points.values() for p in log}
            return {"num_points": num, "num_series": len(series),
                    "num_dropped": self._metric_points_dropped,
                    "nodes": sorted(self._metric_points)}

    # ------------------------------------------------------- alert table

    def list_alerts(self, state: Optional[str] = None,
                    limit: int = 100) -> dict:
        """The live firing view plus the transition log (newest first).
        ``state`` filters the log ("firing" / "resolved")."""
        with self._lock:
            firing = [dict(rec) for rec in self._alerts_active.values()]
            log = [dict(rec) for rec in self._alerts_log]
            dropped = self._alerts_dropped
        firing.sort(key=lambda r: r["since"])
        log.reverse()
        if state is not None:
            log = [rec for rec in log if rec["state"] == state]
        return {"firing": firing, "log": log[:max(0, limit)],
                "num_dropped": dropped}

    def _eval_alerts(self):
        """One alert-engine pass over the metrics table (health-monitor
        thread, alerts_eval_interval_s cadence).  Rule evaluation itself
        is pure (util.alerts); this wrapper snapshots state under _lock,
        evaluates unlocked, then commits transitions and the firing
        gauge."""
        from ray_tpu.util import alerts as alerts_mod

        if not config.alerts:
            return
        rules = alerts_mod.load_rules()
        if not rules:
            return
        now = time.time()

        def query(name, tags, since):
            from ray_tpu.util import metrics_query as mq

            return mq.filter_points(self._metric_points_snapshot(name),
                                    name, tags, since)

        with self._lock:
            active = {k: dict(v) for k, v in self._alerts_active.items()}
        records = alerts_mod.evaluate_rules(rules, query, now, active)
        cap = max(1, config.alerts_table_max)
        with self._lock:
            self._alerts_active = active
            for rec in records:
                if len(self._alerts_log) >= cap:
                    self._alerts_log.popleft()
                    self._alerts_dropped += 1  # eviction, counted
                self._alerts_log.append(rec)
            firing = len(active)
        if self._gm is not None:
            self._gm["alerts_firing"].set(firing)

    # ------------------------- targeted node queries (stacks / logs) ----

    def _node_query_multi(self, node_ids: List[str], kind: str,
                          payload: Optional[dict],
                          timeout_s: float) -> Tuple[Dict[str, Any],
                                                     List[str]]:
        """Publish one targeted ``node_query`` per node and gather the
        ``node_query_report`` replies: ``(reports, missing)``.  The
        introspection analogue of the indirect-probe relay — the GCS
        never dials anyone, the existing pubsub + one-way op carry both
        directions."""
        if not node_ids:
            return {}, []
        with self._lock:
            self._query_seq += 1
            token = f"q{self._query_seq}:{kind}:{time.monotonic():.6f}"
            waiter = {"event": threading.Event(), "reports": {},
                      "want": len(node_ids)}
            self._query_waiters[token] = waiter
        for nid in node_ids:
            self._publish("node_query",
                          {"kind": kind, "token": token,
                           "payload": payload or {}},
                          target_node=nid)
        waiter["event"].wait(max(0.1, timeout_s))
        with self._lock:
            self._query_waiters.pop(token, None)
            reports = dict(waiter["reports"])
        missing = [nid for nid in node_ids if nid not in reports]
        return reports, missing

    def node_query_report(self, token: str, node_id: str, payload):
        """A raylet's reply to a targeted ``node_query`` push."""
        with self._lock:
            waiter = self._query_waiters.get(token)
            if waiter is None:
                return
            waiter["reports"][node_id] = payload
            if len(waiter["reports"]) >= waiter["want"]:
                waiter["event"].set()

    def _alive_node_ids(self, node_id: Optional[str]) -> List[str]:
        with self._lock:
            return [nid for nid, info in self._nodes.items()
                    if info["alive"]
                    and (node_id is None or nid.startswith(node_id))]

    def node_query(self, node_id: Optional[str], kind: str,
                   payload: Optional[dict] = None,
                   timeout_s: float = 3.0) -> Dict[str, Any]:
        """Targeted introspection query against one node (id prefix) or
        every alive node: ``{"reports": {node_id: payload}, "missing":
        [...]}`` — ``missing`` nodes didn't answer inside the timeout
        (dead, partitioned, or busy past the deadline)."""
        targets = self._alive_node_ids(node_id)
        reports, missing = self._node_query_multi(targets, kind, payload,
                                                  timeout_s)
        return {"reports": reports, "missing": missing}

    def collect_stacks(self, node_id: Optional[str] = None,
                       pid: Optional[int] = None,
                       timeout_s: float = 3.0) -> Dict[str, Any]:
        """Live all-thread stacks from every process on the targeted
        node(s) — the cluster-wide ``ray stack`` / ``py-spy dump``
        analogue.  Each raylet dumps its own threads and relays the
        request to its workers over their control sockets; the GCS
        process contributes its own threads unless an in-process raylet
        already covered this pid (embedded single-node mode)."""
        targets = self._alive_node_ids(node_id)
        payload = {"pid": pid} if pid is not None else None
        reports, missing = self._node_query_multi(targets, "stacks",
                                                  payload, timeout_s)
        out = {"nodes": reports, "missing": missing}
        # Embedded-mode dedup: skip the self-dump only when a SAME-HOST
        # report already covers this pid (pids are per-host — a bare
        # cross-node pid match must not silently hide the control plane's
        # stacks, which is exactly what a wedged-GCS debugger came for).
        own_host = socket.gethostname()
        with self._lock:
            same_host = {nid for nid, info in self._nodes.items()
                         if info.get("hostname") == own_host}
        covered = {p.get("pid") for nid, procs in reports.items()
                   if nid in same_host for p in procs or []}
        if node_id is None and os.getpid() not in covered \
                and (pid is None or pid == os.getpid()):
            out["gcs"] = [{"pid": os.getpid(), "proc": "gcs",
                           "threads": profiling.dump_threads(proc="gcs")}]
        return out

    # ----------------------------------------------------------- snapshot

    def state_snapshot(self) -> dict:
        with self._lock:
            pgs = [
                {"pg_id": pid, "state": info["state"],
                 "strategy": info["strategy"],
                 "bundles": info["bundles"],
                 "assignments": {str(k): v
                                 for k, v in info["assignments"].items()}}
                for pid, info in self._cluster_pgs.items()
            ]
            return {
                "nodes": [dict(n) for n in self._nodes.values()],
                "actors": self.list_actors(),
                "placement_groups": pgs,
                "num_objects_tracked": len(self._objects),
                "num_kv": len(self._kv),
            }


# ---------------------------------------------------------------------------
# Socket server


_OPS = {
    "register_node", "unregister_node", "heartbeat", "nodes", "get_node",
    "place_task", "feasible_nodes", "load_metrics",
    "drain_node", "drain_complete", "drain_status",
    "probe_report", "health_stats",
    "kv_put", "kv_multi_put", "kv_get", "kv_del", "kv_keys",
    "put_function", "get_function",
    "register_actor", "update_actor", "remove_actor", "get_actor",
    "lookup_named_actor", "list_actors",
    "add_object_location", "remove_object_location", "get_object_locations",
    "get_object_locations_batch",
    "create_pg", "pg_fragment_ready", "remove_cluster_pg", "pg_info",
    "add_task_events", "list_task_events", "task_events_raw",
    "summarize_task_events",
    "add_trace_spans", "get_trace", "list_trace_spans", "trace_table_stats",
    "add_profile_samples", "list_profile_samples", "profile_table_stats",
    "add_metric_points", "query_metrics", "metrics_table_stats",
    "list_alerts",
    "collect_stacks", "node_query", "node_query_report",
    "state_snapshot",
}

# Ops that BLOCK waiting on node_query_report posts.  They must never run
# on a GcsServer conn thread synchronously: a raylet proxying such a
# gather shares ONE connection with its heartbeats and with the very
# report that completes the gather — serializing them behind the blocked
# op would suspect (then fence) the node and deadlock the query.  The
# server bounces these to a throwaway thread and replies when they finish.
_BLOCKING_OPS = {"collect_stacks", "node_query"}


class GcsServer:
    """TCP front-end for a GcsCore; one reader thread per connection."""

    def __init__(self, core: Optional[GcsCore] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None):
        self.core = core or GcsCore(persist_path=persist_path)
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self._conns: List[socket.socket] = []
        self._stop = False
        self.core.start_health_monitor()
        self.core.start_restart_reconciler()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="gcs-accept", daemon=True)
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self):
        while not self._stop:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(sock)
            threading.Thread(target=self._serve_conn, args=(sock,),
                             name="gcs-serve", daemon=True).start()

    def _serve_conn(self, sock: socket.socket):
        send_lock = make_lock("gcs.server_conn.send")
        push_cb = None
        reader = protocol.FrameReader(sock)
        try:
            while True:
                try:
                    msg = reader.recv_msg()
                except protocol.ProtocolError:
                    break  # desynced peer: drop the connection
                if msg is None:
                    break
                t = msg.get("t")
                if t == "request":
                    rid, op = msg["rid"], msg["op"]
                    if op in _BLOCKING_OPS:
                        # report-waiting gathers run OFF the conn thread:
                        # this connection must stay responsive for the
                        # caller's heartbeats and the node_query_report
                        # frames that complete the very gather (clients
                        # demux replies by rid, so ordering is free)
                        def run_blocking(rid=rid, op=op, msg=msg):
                            try:
                                value = getattr(self.core, op)(
                                    *msg.get("args", ()),
                                    **msg.get("kw", {}))
                                reply = {"t": "reply", "rid": rid,
                                         "ok": True, "value": value}
                            except Exception as e:  # noqa: BLE001
                                reply = {"t": "reply", "rid": rid,
                                         "ok": False, "error": e}
                            try:
                                protocol.send_msg(sock, reply, send_lock)
                            except OSError:
                                pass

                        threading.Thread(target=run_blocking,
                                         name=f"gcs-{op}",
                                         daemon=True).start()
                        continue
                    try:
                        if op == "subscribe":
                            node_id = msg.get("kw", {}).get(
                                "node_id", msg.get("node_id"))

                            def push_cb(event, data, _sl=send_lock, _s=sock):
                                try:
                                    protocol.send_msg(
                                        _s, {"t": "push", "event": event,
                                             "data": data}, _sl)
                                except OSError:
                                    pass

                            self.core.subscribe(push_cb, node_id)
                            value = True
                        elif op in _OPS:
                            value = getattr(self.core, op)(
                                *msg.get("args", ()), **msg.get("kw", {}))
                        else:
                            raise ValueError(f"unknown GCS op {op}")
                        protocol.send_msg(
                            sock, {"t": "reply", "rid": rid, "ok": True,
                                   "value": value}, send_lock)
                    except Exception as e:  # noqa: BLE001
                        try:
                            protocol.send_msg(
                                sock, {"t": "reply", "rid": rid, "ok": False,
                                       "error": e}, send_lock)
                        except OSError:
                            break
        finally:
            if push_cb is not None:
                self.core.unsubscribe(push_cb)
            try:
                sock.close()
            except OSError:
                pass

    def shutdown(self):
        self._stop = True
        self.core.stop()
        try:
            self._listener.close()
        except OSError:
            pass
        for s in self._conns:
            try:
                s.close()
            except OSError:
                pass


class GcsClient:
    """Socket client with the same method surface as GcsCore."""

    def __init__(self, address: str,
                 push_handler: Optional[Callable[[str, Any], None]] = None,
                 timeout: float = 10.0,
                 on_disconnect: Optional[Callable[[], None]] = None):
        host, port = address.rsplit(":", 1)
        self.address = address
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = make_lock("gcs_client.send")
        self._rid = 0  # guard: _rid_lock
        self._rid_lock = make_lock("gcs_client.rid")
        self._pending: Dict[int, dict] = {}
        self._push_handler = push_handler
        self._on_disconnect = on_disconnect
        # Optional latency hook: called as (op, seconds) for every blocking
        # round-trip (the raylet wires it to its internal
        # ray_tpu_internal_gcs_rpc_latency_s histogram).
        self.rpc_observer: Optional[Callable[[str, float], None]] = None
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name="gcs-client", daemon=True)
        self._reader.start()

    def _read_loop(self):
        reader = protocol.FrameReader(self._sock)
        while True:
            try:
                msg = reader.recv_msg()
            except (OSError, protocol.ProtocolError):
                msg = None
            if msg is None:
                was_closed = self._closed
                self._closed = True
                err = ConnectionError("GCS connection lost")
                for entry in list(self._pending.values()):
                    entry["msg"] = {"ok": False, "error": err}
                    entry["event"].set()
                if not was_closed and self._on_disconnect is not None:
                    try:
                        self._on_disconnect()
                    except Exception:  # noqa: BLE001
                        traceback.print_exc()
                return
            if msg.get("t") == "reply":
                entry = self._pending.pop(msg["rid"], None)
                if entry is not None:
                    entry["msg"] = msg
                    entry["event"].set()
            elif msg.get("t") == "push" and self._push_handler is not None:
                try:
                    self._push_handler(msg["event"], msg["data"])
                except Exception:  # noqa: BLE001
                    traceback.print_exc()

    def _call(self, op: str, /, *args, **kw):
        # `op` is positional-only: table ops take an `op=` KWARG of their
        # own (query_metrics op="rate") which must land in **kw, not
        # collide with the method-name parameter
        if self._closed:
            raise ConnectionError("GCS connection lost")
        from ray_tpu.util import tracing as _tracing

        if _tracing.tracing_enabled() \
                and _tracing.current_trace_ctx() is not None:
            # a traced request is on this thread's stack: span the RPC
            # (GCS hops show up in the request waterfall, not just the
            # aggregate latency histogram)
            with _tracing.maybe_span(f"gcs.rpc {op}"):
                return self._call_inner(op, args, kw)
        return self._call_inner(op, args, kw)

    def _call_inner(self, op: str, args, kw):
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        entry = {"event": threading.Event(), "msg": None}
        self._pending[rid] = entry
        t0 = time.perf_counter()
        protocol.send_msg(
            self._sock,
            {"t": "request", "rid": rid, "op": op, "args": args, "kw": kw},
            self._send_lock)
        if not entry["event"].wait(60.0):
            self._pending.pop(rid, None)
            raise TimeoutError(f"GCS op {op} timed out")
        if self.rpc_observer is not None:
            try:
                self.rpc_observer(op, time.perf_counter() - t0)
            except Exception:  # noqa: BLE001
                pass
        msg = entry["msg"]
        if not msg["ok"]:
            raise msg["error"]
        return msg["value"]

    def post(self, op: str, *args, **kw):
        """Fire-and-forget: send the request without registering a pending
        reply (the server's reply is dropped by the reader).  For hot-path
        metadata updates (object locations, actor states) where a blocking
        round-trip from the raylet event thread would serialize completions
        on GCS latency."""
        if self._closed:
            raise ConnectionError("GCS connection lost")
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        protocol.send_msg(
            self._sock,
            {"t": "request", "rid": rid, "op": op, "args": args, "kw": kw},
            self._send_lock)

    def subscribe_remote(self, node_id: Optional[str] = None):
        return self._call("subscribe", node_id=node_id)

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def __getattr__(self, op):
        if op in _OPS:
            return lambda *a, **kw: self._call(op, *a, **kw)
        raise AttributeError(op)
