"""Placement groups (reference: `python/ray/util/placement_group.py:34,139`,
bundle reservation 2PC at `src/ray/raylet/placement_group_resource_manager.cc`).

Single-node round 1: bundles are resource sub-pools carved out of the node's
pool atomically on creation; PACK/SPREAD/STRICT_* strategies are recorded and
become meaningful with multi-node scheduling (ICI-slice-aware packing is the
TPU analogue of NVLink-island STRICT_PACK — see SURVEY.md §7).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.worker import global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self):
        import ray_tpu

        return ray_tpu.put(True)

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        return True

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()}, {self.strategy}, {self.bundle_specs})"

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    worker = global_worker()
    pg_id = PlacementGroupID.from_random()
    if worker.mode == "driver":
        ok = worker.raylet.call(
            worker.raylet.create_pg, pg_id.hex(), bundles, strategy
        ).result()
        if not ok:
            raise ValueError(
                f"placement group {bundles} exceeds cluster capacity "
                f"{worker.raylet.resources_total}"
            )
    elif worker.mode == "local":
        pass
    else:
        raise NotImplementedError(
            "placement_group() from inside tasks is not supported yet"
        )
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup):
    worker = global_worker()
    if worker.mode == "driver":
        worker.raylet.call(worker.raylet.remove_pg, pg.id.hex()).result()


def get_current_placement_group() -> Optional[PlacementGroup]:
    return None
