"""Placement groups (reference: `python/ray/util/placement_group.py:34,139`,
bundle reservation 2PC at `src/ray/raylet/placement_group_resource_manager.cc`).

Single-node: bundles are resource sub-pools carved out of the node's pool
atomically when capacity allows; a PG whose bundles fit total capacity but
not *currently available* resources stays **pending** and is reserved as
resources free up — availability is never driven negative.  ``ready()``
returns an ObjectRef that resolves when the reservation lands (reference
semantics).  PACK/SPREAD/STRICT_* strategies are recorded and become
meaningful with multi-node scheduling (ICI-slice-aware packing is the TPU
analogue of NVLink-island STRICT_PACK — see SURVEY.md §7).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.ids import ObjectID, PlacementGroupID, put_counter
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.worker import global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, ready_oid: Optional[ObjectID] = None):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self._ready_oid = ready_oid

    def ready(self) -> ObjectRef:
        """ObjectRef that resolves to True once every bundle is reserved."""
        if self._ready_oid is not None:
            return ObjectRef(self._ready_oid)
        import ray_tpu

        return ray_tpu.put(True)  # local mode: trivially ready

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        import ray_tpu

        ready, _ = ray_tpu.wait([self.ready()], num_returns=1,
                                timeout=timeout_seconds)
        if not ready:
            return False
        try:
            # The ready object resolves to an error if the PG was removed
            # while still pending — that is NOT "ready".
            return bool(ray_tpu.get(ready[0]))
        except Exception:  # noqa: BLE001
            return False

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()}, {self.strategy}, {self.bundle_specs})"

    def __reduce__(self):
        return (PlacementGroup,
                (self.id, self.bundle_specs, self.strategy, self._ready_oid))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    worker = global_worker()
    pg_id = PlacementGroupID.from_random()
    if worker.mode == "local":
        return PlacementGroup(pg_id, bundles, strategy)
    ready_oid = put_counter.next_object_id()
    if worker.mode == "driver":
        ok = worker.raylet.call(
            worker.raylet.create_pg, pg_id.hex(), bundles, strategy, ready_oid
        ).result()
    else:
        ok = worker._request("create_pg", pg_id=pg_id.hex(), bundles=bundles,
                             strategy=strategy, ready_oid=ready_oid)
    if not ok:
        raise ValueError(
            f"placement group {bundles} exceeds cluster capacity"
        )
    return PlacementGroup(pg_id, bundles, strategy, ready_oid=ready_oid)


def remove_placement_group(pg: PlacementGroup):
    worker = global_worker()
    if worker.mode == "driver":
        worker.raylet.call(worker.raylet.remove_pg, pg.id.hex()).result()
    elif worker.mode in ("worker", "client"):
        worker._request("remove_pg", pg_id=pg.id.hex())


def get_current_placement_group() -> Optional[PlacementGroup]:
    return None
