"""TaskSpec — the unit of work shipped from submitter to executor.

Reference analogue: ``TaskSpecification`` (`src/ray/common/task/task_spec.h`).
Covers normal tasks, actor-creation tasks, and actor method calls.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.config import config
from ray_tpu.core.ids import ActorID, FunctionID, ObjectID, TaskID

_JOB_ID = config.job_id or "driver"


def _default_job_id() -> str:
    """Job attribution for task events: entrypoints launched by the job
    manager carry their submission id in RAY_TPU_JOB_ID (set by the job
    supervisor before the driver process starts, so read-once is safe);
    ad-hoc drivers fall back to one shared bucket."""
    return _JOB_ID

NORMAL_TASK = "normal"
ACTOR_CREATION_TASK = "actor_creation"
ACTOR_TASK = "actor_task"

# num_returns sentinel: the task is a generator streaming its yields as
# they are produced (reference: ``num_returns="streaming"`` /
# ObjectRefGenerator, `python/ray/_raylet.pyx:209,224`).
STREAMING_RETURNS = -1


@dataclass
class TaskSpec:
    task_id: TaskID
    kind: str = NORMAL_TASK
    name: str = ""
    # Either inline pickled function/class bytes, or a FunctionID referencing
    # the GCS function table (large callables are shipped once; reference:
    # `python/ray/_private/function_manager.py`).
    function_blob: Optional[bytes] = None
    function_id: Optional[FunctionID] = None
    # Args: list of ("v", pickled_bytes) inline values or ("ref", ObjectID).
    args: List[Tuple[str, Any]] = field(default_factory=list)
    kwargs: List[Tuple[str, Tuple[str, Any]]] = field(default_factory=list)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retries_left: int = 0
    # Retry on application exceptions too (reference: retry_exceptions=False
    # by default — retries only cover system failures).
    retry_exceptions: bool = False
    # Actor fields
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    max_restarts: int = 0
    max_concurrency: int = 1
    # Named concurrency groups (reference: concurrency_group_manager.cc):
    # creation task carries {"_default": n, "io": 2, ...} — the raylet
    # gates on the SUM; the worker enforces per-group limits with one
    # thread pool per group.  Actor tasks carry their target group.
    concurrency_groups: Optional[Dict[str, int]] = None
    concurrency_group: Optional[str] = None
    # method -> group map (creation task; lets get_actor handles stamp
    # tagged methods' calls with their group)
    method_groups: Optional[Dict[str, str]] = None
    # Eager availability (reference: secondary object copies, SURVEY §5):
    # True = every store-sized return of this task is pushed to a second
    # node when it seals, regardless of the RAY_TPU_REPLICATION_MIN_BYTES
    # auto-threshold (``_replicate=True`` task/actor-method option).
    replicate: bool = False
    # Checkpointable actors (creation task only): snapshot the actor's
    # __ray_save__() state into a replicated object every N completed
    # calls; 0 disables.
    checkpoint_interval: int = 0
    # Actor restart: restore the new instance from this checkpoint object
    # (set by the owning raylet when it resubmits the creation task).
    # Rides dependency_ids() so the ordinary dependency machinery pulls
    # the checkpoint local before dispatch, wherever the restart lands.
    restore_oid: Optional[ObjectID] = None
    # Runtime env (env_vars, working_dir) — per-task override
    runtime_env: Optional[dict] = None
    # Placement: pg id hex + bundle index, or node-affinity
    placement: Optional[dict] = None
    # ObjectIDs of refs serialized INSIDE inline arg values (not declared
    # top-level deps): pinned alongside deps until the task completes so
    # the executor can still resolve them however late it deserializes
    # (borrow pinning; reference: reference_count.h:233).
    inner_refs: Optional[List[ObjectID]] = None
    # Owner bookkeeping
    submitter: str = "driver"
    # Job attribution (GCS task-event table is bounded per job)
    job_id: str = field(default_factory=_default_job_id)
    # Tracing: submit-span context {trace_id, span_id} propagated to the
    # executing worker (reference: span context in task metadata,
    # `tracing_helper.py:289`)
    trace_ctx: Optional[dict] = None
    # End-to-end request deadline: ABSOLUTE wall-clock time.time() at
    # which this task (and everything it spawns — nested submits inherit
    # the tightest enclosing deadline) must be done.  Rides the frame
    # protocol, xtask forwarding and the direct transport like any other
    # spec field; enforced at raylet admission, pre-dispatch, worker
    # pre-exec and mid-exec (reference: Serve request_timeout_s +
    # task cancellation).  None = no deadline.
    deadline: Optional[float] = None
    # TaskID of the task whose execution submitted this one (None for
    # driver submissions): the raylet's cancel fan-out walks this edge so
    # cancel(recursive=True) / deadline expiry reaps downstream work.
    parent_task_id: Optional[TaskID] = None

    # Dynamic attributes (dataclass __dict__ pickles them with the spec):
    #   _direct_generation — actor restart generation stamped by the
    #       owning raylet onto creation specs (the hosted worker validates
    #       direct-call hellos against it) and onto direct-call reconciles.
    #   _direct_retry — this spec reconciles an in-flight DIRECT call
    #       after a channel teardown: the raylet skips it when its returns
    #       already resolved, and fences it (retryable ActorDiedError)
    #       when the actor's generation moved — never a double execution.
    # Scheduler-side transients (_acquired_pool, _batch, _spill_count,
    # _queued_t, _tr_in, _tr_prev) are set and consumed raylet-side.

    def return_ids(self) -> List[ObjectID]:
        if self.num_returns == STREAMING_RETURNS:
            # the completion marker object (stream items are indexed 1..n)
            return [ObjectID.for_task_return(self.task_id, 0)]
        return [
            ObjectID.for_task_return(self.task_id, i)
            for i in range(self.num_returns)
        ]

    def stream_item_id(self, index: int) -> ObjectID:
        """ObjectID of the index-th yielded item (0-based) of a streaming
        task; slot 0 is the completion marker."""
        return ObjectID.for_task_return(self.task_id, index + 1)

    def dependency_ids(self) -> List[ObjectID]:
        deps = [a[1] for a in self.args if a[0] == "ref"]
        deps += [v[1] for _, v in self.kwargs if v[0] == "ref"]
        if self.restore_oid is not None:
            deps.append(self.restore_oid)
        return deps
