"""Result of a training/tuning run (reference: `python/ray/air/result.py`)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Checkpoint]
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    path: Optional[str] = None
    config: Optional[Dict[str, Any]] = None  # trial config (Tune results)

    @property
    def best_checkpoints(self):
        return [self.checkpoint] if self.checkpoint else []
