"""AIR-common layer: Checkpoint, run/scaling/failure configs, Result.

Reference analogues: `python/ray/air/checkpoint.py:66`,
`python/ray/air/config.py:524`, `python/ray/air/result.py` — shared by the
Train and Tune layers.
"""

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.checkpoint_manager import CheckpointManager, TrackedCheckpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "TrackedCheckpoint",
    "FailureConfig",
    "RunConfig",
    "Result",
    "ScalingConfig",
]
