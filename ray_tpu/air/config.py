"""Run/scaling/failure/checkpoint configs.

Reference analogue: `python/ray/air/config.py` (`ScalingConfig`, `RunConfig`,
`FailureConfig :524`, `CheckpointConfig`).  TPU-native addition:
``ScalingConfig.sharding`` carries a `ray_tpu.parallel.ShardingConfig` so the
parallelism strategy (dp/fsdp/tp/pp/sp/ep) is declared where the reference
declares ``use_gpu`` — the worker count scales hosts, the sharding scales
chips within and across them.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many training workers, and what each one needs.

    ``use_tpu``: workers request TPU chips (``resources_per_worker`` may
    override the exact count).  ``devices_per_worker``: virtual CPU device
    count for tests (sets ``--xla_force_host_platform_device_count`` in each
    worker) — on real TPU hosts leave None and the chips visible to the
    process define the local devices.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    devices_per_worker: Optional[int] = None
    placement_strategy: str = "PACK"
    # TPU-native: the parallelism strategy for the global device mesh.
    sharding: Optional[Any] = None  # ray_tpu.parallel.ShardingConfig

    @property
    def _resources_per_worker_not_none(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        return {"CPU": 1.0, "TPU": 1.0} if self.use_tpu else {"CPU": 1.0}

    def as_placement_group_bundles(self):
        return [self._resources_per_worker_not_none
                for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    """max_failures: worker-group restarts before giving up (-1 = infinite)."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # "max" | "min"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1

    def resolved_storage_path(self) -> str:
        """The LOCAL working directory.  A URI storage_path (file://,
        gs://, ... — reference: `tune/syncer.py`) stages locally and the
        controller mirrors it to the URI via the registered Syncer."""
        sp = self.storage_path or ""
        if "://" in sp:
            base = os.path.join(os.path.expanduser("~"),
                                "ray_tpu_results", "_synced")
            # never stage at the SHARED _synced root: an unnamed run would
            # sync every other staged experiment into its own URI
            return os.path.join(base, self.name or "default")
        base = sp or os.path.join(os.path.expanduser("~"),
                                  "ray_tpu_results")
        return os.path.join(base, self.name) if self.name else base

    def storage_uri(self) -> Optional[str]:
        """The remote mirror target (None for plain local paths)."""
        sp = self.storage_path or ""
        if "://" not in sp:
            return None
        return sp.rstrip("/") + "/" + (self.name or "default")
