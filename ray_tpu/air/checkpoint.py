"""Checkpoint — a morphable bundle of training state.

Reference analogue: `python/ray/air/checkpoint.py:66` (dict ⇄ directory ⇄ URI
representations).  TPU-native difference: the dict form holds host numpy
arrays (jax arrays are converted on save so a checkpoint never pins device
memory), and directory serialization is a single msgpack/pickle blob plus
optional raw ``.npy`` files for large arrays — no torch/TF special-casing.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict, Optional

_METADATA_FILE = "ckpt.pkl"


def _to_host(tree):
    """jax arrays → numpy (device→host) so checkpoints don't pin HBM."""
    try:
        import jax
        import numpy as np

        return jax.tree.map(
            lambda x: np.asarray(x) if hasattr(x, "devices") or hasattr(
                x, "addressable_shards") else x,
            tree,
        )
    except Exception:  # noqa: BLE001 - jax not imported/needed
        return tree


class Checkpoint:
    """A checkpoint either holds an in-memory dict or points at a directory."""

    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 path: Optional[str] = None):
        if (data is None) == (path is None):
            raise ValueError("provide exactly one of data / path")
        self._data = data
        self._path = path

    # ------------------------------------------------------------ construct

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=_to_host(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=path)

    # ------------------------------------------------------------ views

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return self._data
        with open(os.path.join(self._path, _METADATA_FILE), "rb") as f:
            return pickle.load(f)

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            if self._path is not None:
                return self._path
            path = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, _METADATA_FILE), "wb") as f:
            pickle.dump(self.to_dict(), f, protocol=pickle.HIGHEST_PROTOCOL)
        return path

    @property
    def is_directory(self) -> bool:
        return self._path is not None

    def __repr__(self):
        kind = f"path={self._path}" if self._path else \
            f"keys={sorted(self.to_dict().keys())}"
        return f"Checkpoint({kind})"

    def __reduce__(self):
        # Ship the data form across processes; directory checkpoints stay
        # path-referenced (shared filesystem assumption, same as reference).
        if self._path is not None:
            return (Checkpoint, (None, self._path))
        return (Checkpoint, (self._data, None))
