"""Checkpoint bookkeeping: persist, rank, prune.

Reference analogue: `python/ray/air/_internal/checkpoint_manager.py:251`
(`_CheckpointManager` ranks by score and prunes to ``num_to_keep``).
"""

from __future__ import annotations

import os
import pickle
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig


@dataclass
class TrackedCheckpoint:
    checkpoint: Checkpoint
    metrics: Dict[str, Any] = field(default_factory=dict)
    index: int = 0
    path: Optional[str] = None


class CheckpointManager:
    """Persists reported checkpoints under ``directory`` and keeps the best
    ``num_to_keep`` by ``checkpoint_score_attribute`` (latest always kept)."""

    def __init__(self, directory: str, config: Optional[CheckpointConfig] = None):
        self.directory = directory
        self.config = config or CheckpointConfig()
        self._index = 0
        self._tracked: List[TrackedCheckpoint] = []
        self.latest: Optional[TrackedCheckpoint] = None
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------------

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> TrackedCheckpoint:
        path = os.path.join(self.directory, f"checkpoint_{self._index:06d}")
        checkpoint.to_directory(path)
        tracked = TrackedCheckpoint(
            checkpoint=Checkpoint.from_directory(path),
            metrics=dict(metrics or {}),
            index=self._index,
            path=path,
        )
        self._index += 1
        self._tracked.append(tracked)
        self.latest = tracked
        self._prune()
        self._write_manifest()
        return tracked

    def _score(self, t: TrackedCheckpoint):
        attr = self.config.checkpoint_score_attribute
        if attr is None or attr not in t.metrics:
            return None
        v = t.metrics[attr]
        return v if self.config.checkpoint_score_order == "max" else -v

    def _prune(self):
        keep = self.config.num_to_keep
        if keep is None or len(self._tracked) <= keep:
            return
        # Latest is always kept; others ranked by score (unscored = oldest
        # first) and the worst dropped.
        candidates = [t for t in self._tracked if t is not self.latest]
        candidates.sort(key=lambda t: (self._score(t) is not None,
                                       self._score(t) or 0, t.index))
        while len(self._tracked) > keep and candidates:
            victim = candidates.pop(0)
            self._tracked.remove(victim)
            if victim.path and os.path.isdir(victim.path):
                shutil.rmtree(victim.path, ignore_errors=True)

    def _write_manifest(self):
        manifest = {
            "time": time.time(),
            "latest": self.latest.path if self.latest else None,
            "tracked": [
                {"path": t.path, "metrics": t.metrics, "index": t.index}
                for t in self._tracked
            ],
            "next_index": self._index,
        }
        tmp = os.path.join(self.directory, ".manifest.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(manifest, f)
        os.replace(tmp, os.path.join(self.directory, "manifest.pkl"))

    # ------------------------------------------------------------------

    @property
    def best(self) -> Optional[TrackedCheckpoint]:
        scored = [t for t in self._tracked if self._score(t) is not None]
        if not scored:
            return self.latest
        return max(scored, key=self._score)

    @classmethod
    def restore(cls, directory: str,
                config: Optional[CheckpointConfig] = None) -> "CheckpointManager":
        """Rebuild manager state from a prior run's manifest (resume path)."""
        mgr = cls(directory, config)
        manifest_path = os.path.join(directory, "manifest.pkl")
        if os.path.exists(manifest_path):
            with open(manifest_path, "rb") as f:
                manifest = pickle.load(f)
            mgr._index = manifest.get("next_index", 0)
            for entry in manifest.get("tracked", []):
                if entry["path"] and os.path.isdir(entry["path"]):
                    t = TrackedCheckpoint(
                        checkpoint=Checkpoint.from_directory(entry["path"]),
                        metrics=entry["metrics"], index=entry["index"],
                        path=entry["path"],
                    )
                    mgr._tracked.append(t)
                    if manifest.get("latest") == entry["path"]:
                        mgr.latest = t
            if mgr.latest is None and mgr._tracked:
                mgr.latest = max(mgr._tracked, key=lambda t: t.index)
        return mgr
