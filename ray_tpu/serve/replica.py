"""Replica — the actor hosting one copy of a deployment's callable.

Reference analogue: `python/ray/serve/_private/replica.py:447`
(``RayServeReplica.handle_request``) — minus the Cython/asyncio plumbing:
requests dispatch through the core actor transport with
``max_concurrency``, and the replica self-reports its in-flight count for
the router's power-of-two probes and the controller's autoscaler.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from ray_tpu.core.config import config

config.define("serve_backpressure", bool, True,
              "Serve overload protection: replicas REJECT requests beyond "
              "max_ongoing_requests with a typed BackPressureError "
              "(router retries another replica, proxy sheds 503) instead "
              "of queueing without bound.  0 restores silent queueing.")


class Replica:
    def __init__(self, deployment_def, init_args, init_kwargs,
                 user_config: Optional[dict] = None,
                 max_ongoing_requests: int = 0,
                 deployment_name: str = "", replica_name: str = ""):
        import cloudpickle

        fn_or_class = cloudpickle.loads(deployment_def)
        self._ongoing = 0
        self._total = 0
        self._rejected = 0
        # 0 = unenforced (legacy replicas / tests constructing directly)
        self._max_ongoing = int(max_ongoing_requests or 0)
        self._lock = threading.Lock()
        self._start_time = time.time()
        self._deployment = deployment_name
        self._tags = {"deployment": deployment_name,
                      "replica": replica_name}
        if deployment_name:
            from ray_tpu.serve.telemetry import set_replica_identity

            set_replica_identity(deployment_name, replica_name)
        if isinstance(fn_or_class, type):
            self._callable = fn_or_class(*init_args, **(init_kwargs or {}))
        else:
            self._callable = fn_or_class
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------- serving

    def _admit(self):
        """max_ongoing_requests admission: REJECT (typed, retryable by the
        router) instead of silently queueing — bounded work is what keeps
        p99 finite under overload (reference: Serve max_ongoing_requests
        backpressure)."""
        from ray_tpu.core.exceptions import BackPressureError

        with self._lock:
            if (self._max_ongoing > 0 and config.serve_backpressure
                    and self._ongoing >= self._max_ongoing):
                self._rejected += 1
                raise BackPressureError(
                    f"replica at max_ongoing_requests="
                    f"{self._max_ongoing} ({self._ongoing} in flight)")
            self._ongoing += 1
            self._total += 1
        self._observe_load()

    def _observe_load(self):
        """Per-replica load gauges: admitted in-flight count plus the
        depth of any @serve.batch queues in this process (the only place
        admitted-but-not-executing requests can park)."""
        if not self._deployment:
            return
        from ray_tpu.serve import batching
        from ray_tpu.serve.telemetry import serve_metrics

        m = serve_metrics()
        m["inflight"].set(float(self._ongoing), tags=self._tags)
        depth = sum(b.queue.qsize() for b in batching._registry.values())
        m["queue"].set(float(depth), tags=self._tags)

    def _chaos_user_call(self):
        """Slow-executor chaos seam INSIDE the admission-counted window
        (the worker-level pre-exec seam sleeps before ``_admit`` runs, so
        it can't pile up ``_ongoing``): matches
        ``RAY_TPU_CHAOS_EXEC_DELAY_NAMES`` substring 'Replica.user' or the
        user callable's own name."""
        from ray_tpu.util import chaos

        name = getattr(self._callable, "__name__",
                       type(self._callable).__name__)
        chaos.exec_delay(f"Replica.user:{name}")

    def handle_request(self, request: Any, method: str = "__call__",
                       multiplexed_model_id: str = ""):
        from ray_tpu.serve.multiplex import _set_model_id

        self._admit()
        token = _set_model_id(multiplexed_model_id)
        try:
            if method == "__call__" and callable(self._callable):
                fn = self._callable  # plain function or __call__ instance
            else:
                fn = getattr(self._callable, method)
            self._chaos_user_call()
            return fn(request)
        finally:
            from ray_tpu.serve.multiplex import _model_id_ctx

            _model_id_ctx.reset(token)
            with self._lock:
                self._ongoing -= 1
            self._observe_load()

    def handle_request_stream(self, request: Any, method: str = "__call__",
                              multiplexed_model_id: str = ""):
        """Generator variant (invoked with num_returns="streaming"): the
        user callable returns an iterator whose items stream to the caller
        as they are produced (reference: Serve streaming responses over
        streaming generator returns)."""
        from ray_tpu.serve.multiplex import _model_id_ctx, _set_model_id
        from ray_tpu.util import tracing

        self._admit()
        token = _set_model_id(multiplexed_model_id)
        try:
            if method == "__call__" and callable(self._callable):
                fn = self._callable
            else:
                fn = getattr(self._callable, method)
            self._chaos_user_call()
            # time-to-first-token: the interval from request entry to the
            # first streamed item, emitted as a sub-span of this call's
            # task.run (the generator body runs inside its context)
            t0 = time.time()
            ttft_ctx = tracing.current_trace_ctx() \
                if tracing.tracing_enabled() else None
            first = True
            for item in fn(request):
                if first:
                    first = False
                    if ttft_ctx is not None:
                        tracing.hop("serve.ttft", ttft_ctx, t0, time.time(),
                                    proc="worker", method=method)
                    if self._deployment:
                        from ray_tpu.serve.telemetry import serve_metrics

                        serve_metrics()["ttft"].observe(
                            time.time() - t0,
                            tags={"deployment": self._deployment})
                yield item
        finally:
            _model_id_ctx.reset(token)
            with self._lock:
                self._ongoing -= 1
            self._observe_load()

    def multiplexed_model_ids(self) -> list:
        """Model ids currently loaded by any @multiplexed method on this
        replica (for tests/state; the reference broadcasts these to the
        router for affinity)."""
        out = []
        cal = self._callable
        for name in dir(type(cal)):
            attr = getattr(type(cal), name, None)
            if callable(attr) and getattr(attr, "_serve_multiplexed", False):
                out.extend(attr._serve_model_ids(cal))
        return out

    # ------------------------------------------------------------- control

    def get_queue_len(self) -> int:
        return self._ongoing

    def stats(self) -> dict:
        return {"ongoing": self._ongoing, "total": self._total,
                "rejected": self._rejected,
                "max_ongoing_requests": self._max_ongoing,
                "uptime_s": time.time() - self._start_time}

    def reconfigure(self, user_config: dict):
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
        return True

    def check_health(self) -> bool:
        if hasattr(self._callable, "check_health"):
            return bool(self._callable.check_health())
        return True
