"""Replica — the actor hosting one copy of a deployment's callable.

Reference analogue: `python/ray/serve/_private/replica.py:447`
(``RayServeReplica.handle_request``) — minus the Cython/asyncio plumbing:
requests dispatch through the core actor transport with
``max_concurrency``, and the replica self-reports its in-flight count for
the router's power-of-two probes and the controller's autoscaler.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional


class Replica:
    def __init__(self, deployment_def, init_args, init_kwargs,
                 user_config: Optional[dict] = None):
        import cloudpickle

        fn_or_class = cloudpickle.loads(deployment_def)
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._start_time = time.time()
        if isinstance(fn_or_class, type):
            self._callable = fn_or_class(*init_args, **(init_kwargs or {}))
        else:
            self._callable = fn_or_class
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------- serving

    def handle_request(self, request: Any, method: str = "__call__",
                       multiplexed_model_id: str = ""):
        from ray_tpu.serve.multiplex import _set_model_id

        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = _set_model_id(multiplexed_model_id)
        try:
            if method == "__call__" and callable(self._callable):
                fn = self._callable  # plain function or __call__ instance
            else:
                fn = getattr(self._callable, method)
            return fn(request)
        finally:
            from ray_tpu.serve.multiplex import _model_id_ctx

            _model_id_ctx.reset(token)
            with self._lock:
                self._ongoing -= 1

    def handle_request_stream(self, request: Any, method: str = "__call__",
                              multiplexed_model_id: str = ""):
        """Generator variant (invoked with num_returns="streaming"): the
        user callable returns an iterator whose items stream to the caller
        as they are produced (reference: Serve streaming responses over
        streaming generator returns)."""
        from ray_tpu.serve.multiplex import _model_id_ctx, _set_model_id
        from ray_tpu.util import tracing

        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = _set_model_id(multiplexed_model_id)
        try:
            if method == "__call__" and callable(self._callable):
                fn = self._callable
            else:
                fn = getattr(self._callable, method)
            # time-to-first-token: the interval from request entry to the
            # first streamed item, emitted as a sub-span of this call's
            # task.run (the generator body runs inside its context)
            t0 = time.time()
            ttft_ctx = tracing.current_trace_ctx() \
                if tracing.tracing_enabled() else None
            first = True
            for item in fn(request):
                if first:
                    first = False
                    if ttft_ctx is not None:
                        tracing.hop("serve.ttft", ttft_ctx, t0, time.time(),
                                    proc="worker", method=method)
                yield item
        finally:
            _model_id_ctx.reset(token)
            with self._lock:
                self._ongoing -= 1

    def multiplexed_model_ids(self) -> list:
        """Model ids currently loaded by any @multiplexed method on this
        replica (for tests/state; the reference broadcasts these to the
        router for affinity)."""
        out = []
        cal = self._callable
        for name in dir(type(cal)):
            attr = getattr(type(cal), name, None)
            if callable(attr) and getattr(attr, "_serve_multiplexed", False):
                out.extend(attr._serve_model_ids(cal))
        return out

    # ------------------------------------------------------------- control

    def get_queue_len(self) -> int:
        return self._ongoing

    def stats(self) -> dict:
        return {"ongoing": self._ongoing, "total": self._total,
                "uptime_s": time.time() - self._start_time}

    def reconfigure(self, user_config: dict):
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
        return True

    def check_health(self) -> bool:
        if hasattr(self._callable, "check_health"):
            return bool(self._callable.check_health())
        return True
