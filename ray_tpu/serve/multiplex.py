"""Model multiplexing: many models share one deployment's replicas.

Reference analogue: `python/ray/serve/multiplex.py` (`@serve.multiplexed`
LRU model loading) + `serve/api.py get_multiplexed_model_id`.  A
deployment method decorated with ``@multiplexed(max_num_models_per_replica
=N)`` is an async-free model loader; each replica keeps an LRU of loaded
models, and requests carry the target model id (HTTP header
``serve_multiplexed_model_id`` or the handle option), which the router
uses for replica affinity — repeat requests for a model land on the
replica that already has it in memory.
"""

from __future__ import annotations

import contextvars
import functools
import threading
from collections import OrderedDict
from typing import Any, Callable

__all__ = ["multiplexed", "get_multiplexed_model_id"]

_model_id_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the CURRENT request (reference:
    ``serve.get_multiplexed_model_id``)."""
    return _model_id_ctx.get()


def _set_model_id(model_id: str):
    return _model_id_ctx.set(model_id)


class _ModelCache:
    """Per-replica LRU of loaded models."""

    def __init__(self, loader: Callable, max_models: int):
        self._loader = loader
        self._max = max_models
        self._models: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def get(self, owner, model_id: str) -> Any:
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        # load OUTSIDE the lock (loads can be slow); last writer wins
        model = self._loader(owner, model_id)
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self._max:
                # Drop the reference and let GC finalize exactly once; an
                # explicit __del__ call here would run it a second time at
                # collection.  Models wanting prompt cleanup define
                # ``unload()``.
                _, evicted = self._models.popitem(last=False)
                unload = getattr(evicted, "unload", None)
                if callable(unload):
                    try:
                        unload()
                    except Exception:  # noqa: BLE001
                        pass
                del evicted
        return model

    def ids(self):
        with self._lock:
            return list(self._models)


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator for a deployment method ``def load(self, model_id) ->
    model`` (reference: `serve/multiplex.py:multiplexed`).  Calling the
    decorated method returns the cached model, loading + LRU-evicting as
    needed."""
    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def deco(func):
        # The cache lives on the replica INSTANCE (created lazily at call
        # time), not in this closure: the deployment class is cloudpickled
        # to replica actors, and a closure-held Lock would break that.
        attr = f"_serve_mux_cache_{func.__name__}"

        def cache_for(self_obj) -> _ModelCache:
            cache = self_obj.__dict__.get(attr)
            if cache is None:
                # dict setdefault is atomic under the GIL: one winner
                cache = self_obj.__dict__.setdefault(
                    attr, _ModelCache(func, max_num_models_per_replica))
            return cache

        @functools.wraps(func)
        def inner(self_obj, model_id: str = None):  # noqa: RUF013
            if model_id is None:
                model_id = get_multiplexed_model_id()
            if not model_id:
                raise ValueError(
                    "no multiplexed model id for this request — send the "
                    "'serve_multiplexed_model_id' header (or model_id "
                    "query param), or set it via handle.options("
                    "multiplexed_model_id=...)")
            return cache_for(self_obj).get(self_obj, model_id)

        inner._serve_multiplexed = True
        inner._serve_model_ids = lambda self_obj: cache_for(self_obj).ids()
        return inner

    return deco
